"""Exporters: schema-versioned JSONL, Prometheus text exposition.

The JSONL exporter is the machine-readable telemetry trail the round-5
VERDICT asked for: every emitted record carries ``schema_version``, the
capture host, and a first-class boolean ``stale`` field (replacing the
ad-hoc "STALE REPLAY" note strings as the *structured* staleness
signal — the human-readable note stays for people reading artifacts).
``bench.py`` routes every line through it, and
``tests/ci/check_bench_schema.py`` validates the output against
:func:`validate_bench_record`.

Chrome-trace export lives on :class:`tracing.SpanRecorder`; this module
adds the registry-wide surfaces: Prometheus text exposition for
scrape-style consumers and a registry→JSONL dump.
"""

from __future__ import annotations

import json
import numbers
import os
import platform
import re
import socket
import sys
from typing import Any, Dict, IO, Iterable, List, Optional

from .metrics import Counter, Gauge, Histogram, MetricsRegistry

__all__ = ["SCHEMA_VERSION", "OVERLAP_MODES", "OVERLAP_SCHEDULE_FIELDS",
           "COMPILE_FIELDS", "TENANT_COUNTS", "CLASS_COUNTS",
           "ADMISSION_MODES",
           "host_info", "JsonlExporter",
           "prometheus_text", "parse_prometheus_text",
           "validate_prometheus_text", "validate_bench_record",
           "validate_bench_jsonl", "validate_lint_record",
           "validate_fleet_record", "validate_trace_record",
           "validate_memory_record", "validate_numerics_record",
           "validate_run_record", "validate_recovery_record",
           "validate_profile_record", "validate_sharding_record",
           "validate_telemetry_record", "validate_telemetry_jsonl"]

# v2: ``kind: fleet`` records REQUIRE ``trace_id`` (the fleet-record
# <-> request-trace join key) and ``kind: trace`` records exist.
# v3: ``kind: memory`` records exist (cost-model/memory-plan dumps);
# fresh ``*_train_throughput`` records must carry the MFU fields
# (``mfu`` / ``achieved_tflops`` / ``flops_per_step`` / ``peak_bytes``)
# and fresh engine-decode records must carry ``kv_cache_bytes``.
# v4: ``kind: numerics`` records exist (gradient-health dumps from
# ``NumericsMonitor.to_record`` / ``bench.py --numerics``) and fresh
# ``numerics_overhead_*`` bench lines must carry ``step_ms_on`` /
# ``step_ms_off`` (an overhead claim is meaningless without both
# sides of the comparison).
# v5: ``kind: run`` records exist (training-run supervisor verdicts
# from ``RunSupervisor.record`` / ``bench.py --run``); fresh
# ``run_supervisor_overhead*`` bench lines must carry ``step_ms_on`` /
# ``step_ms_off`` (same both-sides rule as the v4 numerics overhead);
# ``kind: fleet`` records MAY carry the SLO/goodput fields
# (``goodput_tokens_per_s`` / ``slo_attainment`` /
# ``tokens_within_slo`` / ``deadline_exceeded`` /
# ``deadline_last_sweep``), validated whenever present at any version.
# v6: ``kind: recovery`` records exist (telemetry→action controller
# snapshots from ``fleet.recovery.RecoveryLog.record`` — the elastic
# training controller and the serving SLO-feedback controller — via
# ``bench.py --chaos`` / ``tests/ci/chaos_smoke.py``); fresh
# ``chaos_mttr*`` bench lines must carry ``mttr_s`` and fresh
# ``chaos_spike*`` lines must carry ``slo_attainment`` +
# ``goodput_tokens_per_s`` (a controller-vs-baseline claim is
# meaningless without the SLO side of it); ``kind: fleet`` records MAY
# carry the ``mttr`` aggregate, validated whenever present.
# v7: preemption-safe deterministic resume.  ``kind: recovery``
# records gain ``cause`` (one of RECOVERY_CAUSES — ``preemption`` is
# the planned-SIGTERM exit), ``preempted`` (bool) and ``data_state``
# (the checkpointed sample-stream census:
# samples_consumed/epoch/cursor plus the shard identity), all
# validated whenever present; RECOVERY_ACTION_KINDS grows
# ``preempt_snapshot`` (the coordinated emergency snapshot at the
# step boundary); fresh ``chaos_preempt*`` bench lines must carry
# ``mttr_s`` (preempt request → first committed post-resume step),
# ``resume_overhead_s`` and ``resumed_step`` — a resume-overhead claim
# is meaningless without the resume it measured.
# v8: device-time truth.  ``kind: profile`` records exist (the
# Chrome-trace device-timeline attribution from
# ``observability.timeline``, via ``bench.py --profile`` and the
# ``/profilez`` endpoint): span/busy/compute/collective/gap/overlap
# split in ms plus a MEASURED ``measured_overlap_fraction`` from
# actual kernel-interval overlap — the timeline-backed counterpart of
# steptime's differenced estimate, internally cross-checked by
# ``validate_profile_record``.  Fresh engine-decode bench lines must
# now carry the KV fragmentation pair ``kv_waste_bytes`` +
# ``kv_utilization`` next to v3's ``kv_cache_bytes`` (allocated bytes
# without the wasted bytes is exactly the blind spot ROADMAP item 1's
# paged allocator must drive down); both fields are validated whenever
# present at any version.
# v9: overlapped gradient communication.  Step-time attribution
# records (``train_step_attribution_*`` from ``bench.py --comm``) must
# say WHICH bucket-issue schedule they measured: ``overlap_mode``
# (one of OVERLAP_MODES — ``overlapped`` interleaves per-stage bucket
# reductions with the backward, ``reduce_after_backward`` is the
# classic baseline), ``n_stages`` and the stage-level ``issue_order``
# permutation (OVERLAP_SCHEDULE_FIELDS, duplicated from
# ``observability.steptime`` and pinned equal in tests) — a
# comm-hidden claim is meaningless without the schedule that hid it.
# The fields are validated whenever present at any version; fresh
# v9 attribution lines must carry them.
# v10: the compilation plane.  Fresh train-throughput and engine-decode
# lines must say what their warmup COMPILED — ``cold_compile_ms``
# (trace+lower+compile wall time, separated from every timed rate: the
# PR 4/PR 10 gotcha class of compile seconds folded into a trended
# number), ``compiles_total`` (tracing dispatches during warmup — a
# cold fleet measuring N replica re-jits shows N here, not a mystery
# slowdown) and ``steady_state_retraces`` (compilation-ledger trace
# DELTA across the timed loop, which must be 0: a steady-state retrace
# means the measured rate included a recompile).  All three validated
# whenever present (COMPILE_FIELDS, duplicated from
# observability.compilation.BENCH_COMPILE_FIELDS and pinned equal in
# tests); required on fresh v10 lines; ``supervisor`` anomaly kinds
# grow ``recompilation_storm``.
# v11: the tenant plane.  ``kind: fleet`` records carry the per-tenant
# SLO rollup — a ``tenants`` object keyed by tenant name whose buckets
# hold the TENANT_COUNTS tallies plus ``slo_attainment`` /
# ``goodput_tokens_per_s`` (same nullability/range contract as the
# fleet-level pair), and ``tenants_dropped`` (tenant ids folded into
# the overflow bucket by the label-cardinality cap).  Validated
# whenever present; REQUIRED on fresh v11 fleet records — a fleet
# snapshot that cannot say whose requests it served cannot answer
# "which tenant's p99 regressed".  Untagged requests stay out of the
# map, so per-tenant sums are <= the fleet totals, never ==.  Bench
# grows the two-tenant open-loop leg: fresh ``*_tenant_*_goodput``
# lines must carry ``tenant`` + ``slo_attainment``, and the
# ``*_tenant_parity`` line must carry the token counts its ratio came
# from (``tenants_goodput_tokens`` / ``tokens_within_slo``) and
# reassemble from them.
# v12: the paged serving plane.  Fresh engine-decode lines must say
# HOW their engine admits and holds KV: ``admission_mode`` (one of
# ADMISSION_MODES — ``fixed_slot`` reserves a whole buf_len row per
# request, ``paged`` reserves fixed-size blocks off a shared pool and
# admits at iteration boundaries), so trend tooling never compares a
# paged line against a fixed-slot baseline unknowingly.  Lines from a
# paged engine must additionally carry the pool geometry —
# ``block_size``, ``blocks_total``, ``blocks_free`` (ints,
# blocks_free <= blocks_total) — next to the v8 fragmentation pair
# those fields explain: a falling ``kv_waste_bytes`` claim is
# meaningless without the block size that produced it.  All four are
# validated whenever present at any version; required on fresh v12
# engine-decode lines.
# v13: the sharding plane.  ``kind: sharding`` records exist (the
# static replication ledger from ``analysis.sharding``, via
# ``python -m apex_tpu.analysis --sharding`` and ``bench.py
# --graph-lint``): per entry point, the shard_map world and mesh axes,
# the body-operand byte census split into ``unique_bytes`` +
# ``replicated_bytes`` (world-total duplicate bytes the ZeRO-2/3
# stages of ROADMAP item 2 exist to delete — on the ZeRO-1 DDP train
# EPs this names the fully-replicated fp32 master/optimizer state),
# the per-dtype replicated split, the top replicated arrays with their
# inferred specs, and the resharding-eqn census.  The arithmetic
# identity ``unique_bytes + replicated_bytes == world *
# argument_bytes`` is enforced — a ledger that does not reassemble
# from its own parts is hand-built, not propagated.  Deterministic
# like the compiled memory plan, so ``check_bench_trend`` gates
# ``replicated_bytes`` per entry point on every backend.
# v14: the QoS plane.  ``kind: fleet`` records carry the per-class
# rollup — a ``classes`` object keyed by priority-class name whose
# buckets hold the CLASS_COUNTS tallies (TENANT_COUNTS plus
# ``preempted``: requests evicted mid-decode to admit a higher class)
# alongside ``slo_attainment`` / ``goodput_tokens_per_s`` (fleet-level
# contract) and the live queue shape (``queue_depth`` / ``queue_cap``
# / ``weight`` / ``preemptible``), and a fleet-level ``preemptions``
# total.  Validated whenever present; REQUIRED on fresh v14 fleet
# records — a fleet snapshot that cannot split its SLO story by
# priority class cannot answer "did the batch flood eat the
# interactive tier".  RECOVERY_ACTION_KINDS grows
# ``class_admission_tighten`` / ``class_admission_relax`` (the
# per-class admission knob — the controller squeezes the
# lowest-priority class's queue quota, never rank 0's).  Bench grows
# the QoS leg: fresh per-class ``*_class_*_goodput`` lines must carry
# ``qos_class`` + ``slo_attainment``, and the ``*_preemption_parity``
# line (token-for-token equality of a preempted-then-readmitted
# request vs an undisturbed run) must carry the token counts its
# ratio came from (``matched_tokens`` / ``expected_tokens``), at
# least one measured ``preemptions``, and reassemble from them —
# check_bench_trend gates the parity at exactly 1.0 on EVERY backend
# (determinism, not timing).
# v15: the ZeRO weight-update sharding plane.  Fresh ZeRO bench lines
# (``*zero*_train_throughput`` from the ``ddp_resnet18_o2_zero{1,2,3}``
# / ``ddp_mlp_overlap_zero2`` legs) must carry ``zero_stage`` in
# {1, 2, 3} — a sharded-update throughput number compared against the
# wrong stage's baseline is the exact confusion the replication ledger
# exists to prevent — and ``kind: sharding`` ledger records for zero
# entry points carry the same tag so ``check_bench_trend`` can gate
# ``replicated_bytes`` per (entry_point, backend) on every backend
# with the stage visible in the gated record (the stage-3 ledger
# collapse — masters ARE the params, nothing replicated but BN state
# and scalars — is a per-stage claim, not a per-EP one).  Validated
# whenever present at any version; required on fresh v15 records.
# Validators gate each version's requirements on the record's DECLARED
# version, so archived v1..v14 streams stay valid.
SCHEMA_VERSION = 15

# how a serving engine admits requests and holds KV (stdlib-side
# duplicate of the serving engines' ``admission_mode`` class attrs —
# this module must stay importable without jax; tests pin them in sync)
ADMISSION_MODES = ("fixed_slot", "paged")

# the compile-plane bench fields (stdlib-side duplicate of
# observability.compilation.BENCH_COMPILE_FIELDS — this module must
# stay importable without jax; tests pin the tuples equal)
COMPILE_FIELDS = ("cold_compile_ms", "compiles_total",
                  "steady_state_retraces")

# which bucket-issue schedule an attribution record measured — the
# stdlib-side duplicate of parallel.distributed.OVERLAP_MODES /
# observability.steptime.OVERLAP_SCHEDULE_FIELDS (this module must
# stay importable without jax; tests pin the tuples equal)
OVERLAP_MODES = ("overlapped", "reduce_after_backward")
OVERLAP_SCHEDULE_FIELDS = ("overlap_mode", "n_stages", "issue_order")

_host_info_cache: Optional[Dict[str, Any]] = None


def host_info() -> Dict[str, Any]:
    """Capture-host provenance stamped onto every exported record."""
    global _host_info_cache
    if _host_info_cache is None:
        _host_info_cache = {
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "platform": sys.platform,
            "python": platform.python_version(),
        }
    return dict(_host_info_cache)


class JsonlExporter:
    """Write records as schema-versioned JSON lines.

    ``enrich`` fills only *missing* fields: a replayed record that
    already carries ``stale: true`` / the capture host of the original
    measurement keeps that provenance instead of being restamped.
    """

    def __init__(self, path: Optional[str] = None,
                 stream: Optional[IO[str]] = None):
        if (path is None) == (stream is None):
            raise ValueError("exactly one of path/stream required")
        self._stream = stream
        self._path = path
        self._file: Optional[IO[str]] = None

    @staticmethod
    def enrich(record: Dict[str, Any], stale: bool = False
               ) -> Dict[str, Any]:
        out = dict(record)
        out.setdefault("schema_version", SCHEMA_VERSION)
        out.setdefault("host", host_info())
        out.setdefault("stale", bool(stale))
        out["stale"] = bool(out["stale"])
        return out

    def _out(self) -> IO[str]:
        if self._stream is not None:
            return self._stream
        if self._file is None:
            self._file = open(self._path, "a")
        return self._file

    def emit(self, record: Dict[str, Any], stale: bool = False
             ) -> Dict[str, Any]:
        line = self.enrich(record, stale=stale)
        out = self._out()
        out.write(json.dumps(line) + "\n")
        out.flush()
        return line

    def emit_registry(self, registry: MetricsRegistry,
                      **extra) -> List[Dict[str, Any]]:
        """One record per metric (histograms as their summary)."""
        lines = []
        for m in registry.collect():
            rec = {"metric": m.name, "kind": m.kind, **extra}
            if isinstance(m, Histogram):
                rec.update(m.summary())
            else:
                rec["value"] = m.value
            lines.append(self.emit(rec))
        return lines

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- Prometheus text exposition ------------------------------------------

def _escape_label_value(v) -> str:
    """Exposition-format label-value escaping: backslash, double quote
    and newline must be escaped or a label like ``layer="conv\\1"`` /
    a path with a quote corrupts every line after it."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _unescape_label_value(v: str) -> str:
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt,
                                                             c + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _escape_help(h: str) -> str:
    """HELP text escaping (backslash + newline; quotes are legal
    there)."""
    return h.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_labels(label_set) -> str:
    if not label_set:
        return ""
    return "{" + ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in label_set) + "}"


def _edge_str(e: float) -> str:
    return repr(e) if e != int(e) else str(int(e))


def _expose_one(lines: List[str], m, label_set=()):
    if isinstance(m, Histogram):
        acc = 0
        with m._lock:
            counts, total, n = list(m._counts), m._sum, m._count
        for e, c in zip(m.edges, counts):
            acc += c
            ls = tuple(label_set) + (("le", _edge_str(e)),)
            lines.append(f"{m.name}_bucket{_fmt_labels(ls)} {acc}")
        ls = tuple(label_set) + (("le", "+Inf"),)
        lines.append(f"{m.name}_bucket{_fmt_labels(ls)} {acc + counts[-1]}")
        lines.append(f"{m.name}_sum{_fmt_labels(label_set)} {total}")
        lines.append(f"{m.name}_count{_fmt_labels(label_set)} {n}")
    else:
        lines.append(f"{m.name}{_fmt_labels(label_set)} {m.value}")


def prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Registry contents in the Prometheus text exposition format
    (labeled children exported under the parent name)."""
    from .metrics import get_registry
    reg = registry or get_registry()
    lines: List[str] = []
    for m in reg.collect():
        if m.help:
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        children = m.children()
        # a parent that only ever fans out to labeled children (bare
        # value untouched) contributes no unlabeled sample
        untouched = (m.count == 0 if isinstance(m, Histogram)
                     else m.value == 0)
        if not (children and untouched):
            _expose_one(lines, m)
        for key, child in sorted(children.items()):
            _expose_one(lines, child, key)
    return "\n".join(lines) + "\n"


# a sample line: name, optional {labels}, value.  Label values are
# double-quoted with \\ \" \n escapes (the regex accepts any escaped
# char and _unescape_label_value resolves it).
_PROM_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*)\})?'
    r'\s+(\S+)\s*$')
_PROM_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
# suffixes a histogram family's samples may carry
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse one text exposition into
    ``{family: {type, help, samples: [(name, labels, value)]}}`` with
    label values UNESCAPED — the round-trip half of the conformance
    test.  Raises ``ValueError`` on a malformed line (the validator
    wrapper reports instead)."""
    families: Dict[str, Dict[str, Any]] = {}

    def fam(name):
        return families.setdefault(
            name, {"type": None, "help": None, "samples": []})

    for i, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):].split(" ", 1)
            fam(rest[0])["help"] = (rest[1] if len(rest) > 1 else "")
            continue
        if line.startswith("# TYPE "):
            rest = line[len("# TYPE "):].split(" ", 1)
            if len(rest) != 2:
                raise ValueError(f"line {i}: malformed TYPE: {raw!r}")
            fam(rest[0])["type"] = rest[1]
            continue
        if line.startswith("#"):
            continue                     # plain comment
        m = _PROM_SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i}: not a valid sample: {raw!r}")
        name, labels_raw, value_raw = m.groups()
        try:
            value = float(value_raw.replace("+Inf", "inf")
                          .replace("-Inf", "-inf"))
        except ValueError:
            raise ValueError(f"line {i}: non-numeric value "
                             f"{value_raw!r}") from None
        labels = {k: _unescape_label_value(v)
                  for k, v in _PROM_LABEL_RE.findall(labels_raw or "")}
        base = name
        for sfx in _HIST_SUFFIXES:
            if name.endswith(sfx) and name[:-len(sfx)] in families:
                base = name[:-len(sfx)]
                break
        fam(base)["samples"].append((name, labels, value))
    return families


def validate_prometheus_text(text: str) -> List[str]:
    """Exposition-format conformance check (the `/metricsz` contract,
    shared by the pytest round-trip and tests/ci/server_smoke.py):
    every line parses; every sample belongs to a ``# TYPE``-declared
    family; counters never go negative; histogram families expose a
    ``+Inf`` bucket per label set, cumulative bucket counts that are
    monotone over ascending ``le`` edges, and ``_count`` equal to the
    ``+Inf`` bucket; label values survive the escape round-trip (the
    parser has already unescaped them — a raw quote/newline would have
    failed the parse)."""
    errs: List[str] = []
    try:
        families = parse_prometheus_text(text)
    except ValueError as e:
        return [str(e)]
    for name, f in sorted(families.items()):
        if f["type"] is None:
            errs.append(f"{name}: samples with no # TYPE line")
            continue
        if f["type"] not in ("counter", "gauge", "histogram",
                             "summary", "untyped"):
            errs.append(f"{name}: unknown type {f['type']!r}")
        if f["type"] == "counter":
            for sname, labels, value in f["samples"]:
                if value < 0:
                    errs.append(f"{name}: counter sample {sname} "
                                f"{labels} is negative ({value})")
        if f["type"] != "histogram":
            for sname, labels, _ in f["samples"]:
                if sname != name:
                    errs.append(f"{name}: unexpected sample name "
                                f"{sname!r} for a {f['type']}")
            continue
        # histogram: group buckets by their non-le label set
        series: Dict[tuple, Dict[str, Any]] = {}
        for sname, labels, value in f["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            s = series.setdefault(key, {"buckets": [], "sum": None,
                                        "count": None})
            if sname == name + "_bucket":
                if "le" not in labels:
                    errs.append(f"{name}: bucket sample missing le "
                                f"label ({labels})")
                    continue
                le = labels["le"]
                edge = float("inf") if le == "+Inf" else float(le)
                s["buckets"].append((edge, value))
            elif sname == name + "_sum":
                s["sum"] = value
            elif sname == name + "_count":
                s["count"] = value
            else:
                errs.append(f"{name}: unexpected histogram sample "
                            f"{sname!r}")
        for key, s in sorted(series.items()):
            lbl = dict(key)
            buckets = sorted(s["buckets"])
            if not buckets or buckets[-1][0] != float("inf"):
                errs.append(f"{name}{lbl}: histogram has no +Inf "
                            f"bucket")
                continue
            prev = None
            for edge, c in buckets:
                if prev is not None and c < prev:
                    errs.append(f"{name}{lbl}: cumulative bucket "
                                f"counts decrease at le={edge}")
                prev = c
            if s["count"] is None or s["sum"] is None:
                errs.append(f"{name}{lbl}: histogram missing _sum or "
                            f"_count")
            elif s["count"] != buckets[-1][1]:
                errs.append(f"{name}{lbl}: _count ({s['count']}) != "
                            f"+Inf bucket ({buckets[-1][1]})")
    return errs


# -- bench record schema --------------------------------------------------

def _need(rec, errs, key, types, allow_none=False):
    """Shared required-key type check (bool is not an int here)."""
    if key not in rec:
        errs.append(f"missing required key {key!r}")
        return None
    v = rec[key]
    if v is None and allow_none:
        return v
    if not isinstance(v, types) or isinstance(v, bool) != (types is bool):
        errs.append(f"{key!r} must be {types}, got {type(v).__name__}")
    return v


def _check_kv_fields(rec, errs):
    """The KV fragmentation field contract, shared by bench and
    profile records (one implementation so the two schemas cannot
    drift): byte fields are non-negative ints, waste is a subset of
    the allocation, utilization is a fraction — all validated
    whenever present."""
    for opt in ("kv_cache_bytes", "kv_waste_bytes"):
        if opt in rec:
            v = rec[opt]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{opt!r} must be an int >= 0, got {v!r}")
    kvw, kvc = rec.get("kv_waste_bytes"), rec.get("kv_cache_bytes")
    if (isinstance(kvw, int) and isinstance(kvc, int)
            and not isinstance(kvw, bool) and not isinstance(kvc, bool)
            and kvw > kvc):
        errs.append(f"kv_waste_bytes ({kvw}) exceeds kv_cache_bytes "
                    f"({kvc}) — waste is a subset of the allocation")
    if "kv_utilization" in rec:
        v = rec["kv_utilization"]
        if (not isinstance(v, numbers.Number) or isinstance(v, bool)
                or not (0.0 <= v <= 1.0)):
            errs.append(f"'kv_utilization' must be in [0, 1], got "
                        f"{v!r}")


def _check_block_pool_fields(rec, errs):
    """The paged-KV field contract (schema v12), validated whenever
    present at any version: ``admission_mode`` names a known mode;
    ``block_size`` is a positive int; ``blocks_total`` /
    ``blocks_free`` are non-negative ints with free <= total (free
    blocks beyond the pool would mean the allocator double-freed)."""
    if "admission_mode" in rec:
        am = rec["admission_mode"]
        if am not in ADMISSION_MODES:
            errs.append(f"'admission_mode' must be one of "
                        f"{ADMISSION_MODES}, got {am!r}")
    if "block_size" in rec:
        v = rec["block_size"]
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errs.append(f"'block_size' must be an int >= 1, got {v!r}")
    for key in ("blocks_total", "blocks_free"):
        if key in rec:
            v = rec[key]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{key!r} must be an int >= 0, got {v!r}")
    bf, bt = rec.get("blocks_free"), rec.get("blocks_total")
    if (isinstance(bf, int) and isinstance(bt, int)
            and not isinstance(bf, bool) and not isinstance(bt, bool)
            and bf > bt):
        errs.append(f"blocks_free ({bf}) exceeds blocks_total ({bt}) "
                    f"— free blocks are a subset of the pool")


def _check_compile_fields(rec, errs):
    """The compilation-plane field contract (schema v10), validated
    whenever present: ``cold_compile_ms`` is a non-negative number,
    ``compiles_total`` / ``steady_state_retraces`` non-negative ints.
    (Whether a nonzero steady-state retrace count GATES is the trend
    checker's job — schema-wise the record is honest about it.)"""
    if "cold_compile_ms" in rec:
        v = rec["cold_compile_ms"]
        if (not isinstance(v, numbers.Number) or isinstance(v, bool)
                or not (v >= 0)):
            errs.append(f"'cold_compile_ms' must be a number >= 0, "
                        f"got {v!r}")
    for key in ("compiles_total", "steady_state_retraces"):
        if key in rec:
            v = rec[key]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{key!r} must be an int >= 0, got {v!r}")


def _check_envelope(rec, errs):
    """The common record envelope every exported line carries
    (schema_version / capture host / first-class ``stale``) — one
    implementation for bench and lint records."""
    sv = _need(rec, errs, "schema_version", int)
    if isinstance(sv, int) and not isinstance(sv, bool) and sv < 1:
        errs.append(f"schema_version must be >= 1, got {sv}")
    _need(rec, errs, "stale", bool)
    host = _need(rec, errs, "host", dict)
    if isinstance(host, dict):
        if not isinstance(host.get("hostname"), str):
            errs.append("host.hostname must be a string")
        if not isinstance(host.get("pid"), int):
            errs.append("host.pid must be an int")


def validate_bench_record(rec: Any) -> List[str]:
    """Schema check for one bench JSONL record; returns a list of
    problems (empty = valid).  Shared by the pytest coverage and the
    tests/ci/check_bench_schema.py gate."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types, allow_none=False):
        return _need(rec, errs, key, types, allow_none)

    _check_envelope(rec, errs)
    metric = need("metric", str)
    if isinstance(metric, str) and not metric:
        errs.append("metric must be non-empty")
    need("value", numbers.Number, allow_none=True)
    need("unit", str, allow_none=True)
    need("backend", str)
    need("ndev", int)
    need("arch", str)
    for opt in ("note", "error", "recorded_at", "stale_recorded_at"):
        if opt in rec and not isinstance(rec[opt], str):
            errs.append(f"{opt!r} must be a string when present")
    if "vs_baseline" in rec and rec["vs_baseline"] is not None \
            and not isinstance(rec["vs_baseline"], numbers.Number):
        errs.append("'vs_baseline' must be a number or null")
    # serving decode-window fields (PR 2): ``window`` is the in-graph
    # decode ticks per host sync — tokens/sec lines are only comparable
    # given it, so fresh engine-decode measurements must carry it.
    # Stale replays of pre-window records and error lines are exempt.
    if "window" in rec:
        w = rec["window"]
        if not isinstance(w, int) or isinstance(w, bool) or w < 1:
            errs.append(f"'window' must be an int >= 1, got {w!r}")
    if "tokens_per_sync" in rec and not isinstance(
            rec["tokens_per_sync"], numbers.Number):
        errs.append("'tokens_per_sync' must be a number when present")
    sv_rec = rec.get("schema_version")
    v3 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
          and sv_rec >= 3)
    v8 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
          and sv_rec >= 8)
    v10 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
           and sv_rec >= 10)
    v12 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
           and sv_rec >= 12)
    if (isinstance(metric, str) and "engine_decode" in metric
            and "error" not in rec and not rec.get("stale")):
        if "window" not in rec:
            errs.append("engine decode records must carry 'window' "
                        "(decode ticks per host sync)")
        unit = rec.get("unit")
        if isinstance(unit, str) and "tokens/sec" not in unit:
            errs.append(f"engine decode records must report a "
                        f"tokens/sec unit, got {unit!r}")
        if v3 and "kv_cache_bytes" not in rec:
            errs.append("fresh engine decode records must carry "
                        "'kv_cache_bytes' (schema v3)")
        # v8: allocated bytes without the wasted bytes is exactly the
        # fragmentation blind spot — fresh decode lines carry the pair
        if v8:
            for key in ("kv_waste_bytes", "kv_utilization"):
                if key not in rec:
                    errs.append(f"fresh engine decode records must "
                                f"carry {key!r} (schema v8)")
        # v10: a decode rate is only a steady-state claim if it says
        # what warmup compiled and that the timed loop re-traced
        # nothing — the compile-plane triple
        if v10:
            for key in COMPILE_FIELDS:
                if key not in rec:
                    errs.append(f"fresh engine decode records must "
                                f"carry {key!r} (schema v10)")
        # v12: the paged serving plane — a decode line must say HOW
        # its engine admits and holds KV (a paged line compared
        # against a fixed-slot baseline unknowingly is the trend
        # checker's blind spot), and a paged line must carry the pool
        # geometry its fragmentation numbers are denominated in
        if v12:
            if "admission_mode" not in rec:
                errs.append("fresh engine decode records must carry "
                            "'admission_mode' (schema v12)")
            elif rec.get("admission_mode") == "paged":
                for key in ("block_size", "blocks_total",
                            "blocks_free"):
                    if key not in rec:
                        errs.append(f"fresh paged engine decode "
                                    f"records must carry {key!r} "
                                    f"(schema v12)")
    # MFU / peak-memory fields (PR 8): a fresh train-step throughput
    # line is only a roofline statement given the model FLOPs behind
    # it — v3 records must say what they computed (flops_per_step,
    # per device), how fast (achieved_tflops, mfu vs the costmodel
    # peak table — null where the table has no entry for the
    # hardware) and at what memory high-water mark (peak_bytes from
    # the compiled plan).  Stale replays of older rounds and error
    # lines stay exempt, as does anything declaring schema_version < 3.
    if (v3 and isinstance(metric, str)
            and metric.endswith("_train_throughput")
            and "error" not in rec and not rec.get("stale")):
        for key in ("flops_per_step", "achieved_tflops"):
            v = _need(rec, errs, key, numbers.Number)
            if (isinstance(v, numbers.Number) and not isinstance(v, bool)
                    and v < 0):
                errs.append(f"{key!r} must be >= 0, got {v}")
        mv = _need(rec, errs, "mfu", numbers.Number, allow_none=True)
        if (isinstance(mv, numbers.Number) and not isinstance(mv, bool)
                and mv < 0):
            errs.append(f"'mfu' must be >= 0 or null, got {mv}")
        pb = _need(rec, errs, "peak_bytes", int)
        if isinstance(pb, int) and not isinstance(pb, bool) and pb < 0:
            errs.append(f"'peak_bytes' must be >= 0, got {pb}")
    # v10: fresh train-throughput lines carry the compile-plane triple
    # next to the v3 cost-model fields — a timed rate that cannot say
    # its compile time was separated out is the gotcha class bench
    # exists to prevent (cold compiles folded into trended numbers)
    if (v10 and isinstance(metric, str)
            and metric.endswith("_train_throughput")
            and "error" not in rec and not rec.get("stale")):
        for key in COMPILE_FIELDS:
            if key not in rec:
                errs.append(f"fresh train-throughput records must "
                            f"carry {key!r} (schema v10)")
    _check_kv_fields(rec, errs)
    _check_compile_fields(rec, errs)
    _check_block_pool_fields(rec, errs)
    if "mfu" in rec and rec["mfu"] is not None and (
            not isinstance(rec["mfu"], numbers.Number)
            or isinstance(rec["mfu"], bool)):
        errs.append("'mfu' must be a number or null")
    # gradient-allreduce comm microbench fields (bench.py --comm): a
    # record carrying ``comm_topology`` describes one topology variant
    # of the two-level ICI/DCN reduction and must state the per-level
    # wire bytes — the flat-vs-hierarchical comparison is meaningless
    # without them — plus the compression flag and the level widths.
    if "comm_topology" in rec:
        ct = rec["comm_topology"]
        if ct not in ("flat", "hierarchical"):
            errs.append(f"'comm_topology' must be 'flat' or "
                        f"'hierarchical', got {ct!r}")
        _need(rec, errs, "compress", bool)
        for key in ("ici_size", "dcn_size"):
            v = _need(rec, errs, key, int)
            if isinstance(v, int) and not isinstance(v, bool) and v < 1:
                errs.append(f"{key!r} must be >= 1, got {v}")
        for key in ("wire_bytes", "ici_wire_bytes", "dcn_wire_bytes"):
            v = _need(rec, errs, key, int)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                errs.append(f"{key!r} must be >= 0, got {v}")
    if (isinstance(metric, str) and metric.startswith("grad_allreduce_")
            and "error" not in rec and not rec.get("stale")
            and "comm_topology" not in rec):
        errs.append("grad_allreduce records must carry 'comm_topology' "
                    "(and the per-level wire-byte fields)")
    # numerics-instrumentation overhead fields (bench.py --numerics,
    # schema v4): an overhead line is the on-vs-off step-time
    # comparison — both sides must be on the record, non-negative,
    # and arithmetically consistent with the headline value.
    for opt in ("step_ms_on", "step_ms_off", "overhead_fraction"):
        if opt in rec:
            v = rec[opt]
            if (not isinstance(v, numbers.Number)
                    or isinstance(v, bool) or v < 0):
                errs.append(f"{opt!r} must be a number >= 0 when "
                            f"present, got {v!r}")
    v4 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
          and sv_rec >= 4)
    v5 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
          and sv_rec >= 5)
    # the v5 supervisor-overhead lines (bench.py --run) follow the
    # same both-sides contract as the v4 numerics overhead: an
    # overhead claim must carry the on and off step times it came from
    if (isinstance(metric, str)
            and ((v4 and metric.startswith("numerics_overhead"))
                 or (v5 and metric.startswith("run_supervisor_overhead")))
            and "error" not in rec and not rec.get("stale")):
        on = _need(rec, errs, "step_ms_on", numbers.Number)
        off = _need(rec, errs, "step_ms_off", numbers.Number)
        val = rec.get("value")
        ok_num = all(isinstance(v, numbers.Number)
                     and not isinstance(v, bool)
                     for v in (on, off, val))
        if ok_num:
            # the headline must reassemble from its own sides (bench
            # clamps negative overhead to 0 and rounds to 4 decimals
            # — 0.01 ms absorbs the rounding, nothing else)
            expect = max(on - off, 0.0)
            if abs(val - expect) > max(0.01, 0.01 * expect):
                errs.append(
                    f"value ({val}) inconsistent with "
                    f"step_ms_on - step_ms_off ({on} - {off})")
            frac = rec.get("overhead_fraction")
            if (isinstance(frac, numbers.Number)
                    and not isinstance(frac, bool) and off > 0
                    and abs(frac - expect / off)
                    > max(0.01, 0.01 * frac)):
                errs.append(
                    f"overhead_fraction ({frac}) inconsistent with "
                    f"value/step_ms_off ({expect:.4g}/{off})")
        if "opt_level" in rec and not isinstance(rec["opt_level"], str):
            errs.append("'opt_level' must be a string when present")
    # chaos lines (bench.py --chaos, schema v6): the MTTR line must
    # carry the measurement it claims, and the spike lines must carry
    # the SLO side of the controller-vs-baseline comparison
    v6 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
          and sv_rec >= 6)
    if (v6 and isinstance(metric, str)
            and "error" not in rec and not rec.get("stale")):
        if metric.startswith("chaos_mttr"):
            v = _need(rec, errs, "mttr_s", numbers.Number)
            if (isinstance(v, numbers.Number)
                    and not isinstance(v, bool) and not (v >= 0)):
                errs.append(f"'mttr_s' must be >= 0, got {v!r}")
        if metric.startswith("chaos_spike"):
            att = _need(rec, errs, "slo_attainment", numbers.Number,
                        allow_none=True)
            if (isinstance(att, numbers.Number)
                    and not isinstance(att, bool)
                    and not (0.0 <= att <= 1.0)):
                errs.append(f"'slo_attainment' must be null or in "
                            f"[0, 1], got {att!r}")
            gp = _need(rec, errs, "goodput_tokens_per_s",
                       numbers.Number)
            if (isinstance(gp, numbers.Number)
                    and not isinstance(gp, bool) and not (gp >= 0)):
                errs.append(f"'goodput_tokens_per_s' must be >= 0, "
                            f"got {gp!r}")
    # preemption resume lines (bench.py --chaos, schema v7): the
    # trend-gated resume-overhead claim must carry the resume it
    # measured — the MTTR window (preempt request → first committed
    # post-resume step), the restore overhead, and where it resumed
    v7 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
          and sv_rec >= 7)
    if (v7 and isinstance(metric, str)
            and metric.startswith("chaos_preempt")
            and "error" not in rec and not rec.get("stale")):
        for key in ("mttr_s", "resume_overhead_s"):
            v = _need(rec, errs, key, numbers.Number)
            if (isinstance(v, numbers.Number)
                    and not isinstance(v, bool) and not (v >= 0)):
                errs.append(f"{key!r} must be >= 0, got {v!r}")
        rs = _need(rec, errs, "resumed_step", int)
        if isinstance(rs, int) and not isinstance(rs, bool) and rs < 0:
            errs.append(f"'resumed_step' must be >= 0, got {rs}")
    # step-time attribution fields (bench.py --comm, PR 6): a record
    # carrying ``overlap_fraction`` decomposes a train step into
    # compute vs comm time per fabric level and must be internally
    # consistent — compute + critical-path comm reassemble the
    # wall-clock step, the per-level times reassemble the isolated
    # comm measurement, and the overlap fraction is a fraction.
    if "overlap_fraction" in rec:
        for key in ("step_ms", "compute_ms", "comm_ms",
                    "comm_isolated_ms", "ici_ms", "dcn_ms",
                    "overlap_fraction"):
            v = _need(rec, errs, key, numbers.Number)
            if (isinstance(v, numbers.Number) and not isinstance(v, bool)
                    and v < 0):
                errs.append(f"{key!r} must be >= 0, got {v}")
        vals = {k: rec.get(k) for k in ("step_ms", "compute_ms",
                                        "comm_ms", "comm_isolated_ms",
                                        "ici_ms", "dcn_ms",
                                        "overlap_fraction")}
        if all(isinstance(v, numbers.Number) and not isinstance(v, bool)
               for v in vals.values()):
            if vals["overlap_fraction"] > 1.0:
                errs.append(f"overlap_fraction must be in [0, 1], got "
                            f"{vals['overlap_fraction']}")
            # comm_ms is the CLAMPED step-compute difference, so the
            # only legitimate residue is measurement noise when the
            # compute twin times slower than the full step
            resid = abs(vals["compute_ms"] + vals["comm_ms"]
                        - vals["step_ms"])
            if resid > max(0.25 * vals["step_ms"], 0.25):
                errs.append(
                    f"compute_ms + comm_ms ({vals['compute_ms']} + "
                    f"{vals['comm_ms']}) inconsistent with step_ms "
                    f"({vals['step_ms']})")
            lvl = abs(vals["ici_ms"] + vals["dcn_ms"]
                      - vals["comm_isolated_ms"])
            if lvl > max(0.02 * vals["comm_isolated_ms"], 0.01):
                errs.append(
                    f"ici_ms + dcn_ms ({vals['ici_ms']} + "
                    f"{vals['dcn_ms']}) must reassemble "
                    f"comm_isolated_ms ({vals['comm_isolated_ms']})")
    # overlap schedule fields (PR 14, schema v9): a record saying WHICH
    # bucket-issue schedule it measured must say it coherently — a
    # known mode, a positive stage count, and a stage-level issue order
    # that is a permutation of the stages.  Validated whenever present;
    # REQUIRED on fresh v9 train_step_attribution_* lines (a
    # comm-hidden claim without its schedule is not comparable).
    if "overlap_mode" in rec:
        om = rec["overlap_mode"]
        if om not in OVERLAP_MODES:
            errs.append(f"'overlap_mode' must be one of "
                        f"{OVERLAP_MODES}, got {om!r}")
        # a mode claim needs its schedule shape alongside it
        _need(rec, errs, "n_stages", int)
        _need(rec, errs, "issue_order", list)
    # the shape fields are coherence-checked WHENEVER present — a
    # record carrying n_stages=0 or a non-permutation issue_order is
    # incoherent whether or not it also names its mode
    ns = rec.get("n_stages")
    ns_ok = isinstance(ns, int) and not isinstance(ns, bool)
    if "n_stages" in rec:
        if not ns_ok:
            errs.append(f"'n_stages' must be an int, got {ns!r}")
        elif ns < 1:
            errs.append(f"'n_stages' must be >= 1, got {ns}")
    if "issue_order" in rec:
        io = rec["issue_order"]
        if not isinstance(io, list) or not all(
                isinstance(s, int) and not isinstance(s, bool)
                for s in io):
            errs.append("'issue_order' must be a list of ints")
        elif ns_ok and ns >= 1 and sorted(io) != list(range(ns)):
            errs.append(
                f"'issue_order' must be a permutation of the "
                f"{ns} stage ids, got {io}")
    v9 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
          and sv_rec >= 9)
    if (v9 and isinstance(metric, str)
            and metric.startswith("train_step_attribution")
            and "error" not in rec and not rec.get("stale")):
        for key in OVERLAP_SCHEDULE_FIELDS:
            if key not in rec:
                errs.append(f"fresh step-attribution records must "
                            f"carry {key!r} (schema v9: which "
                            f"bucket-issue schedule was measured)")
    # tenant-tagged bench lines (bench.py --fleet two-tenant leg,
    # schema v11): whenever a line names a tenant it must name it
    # coherently, and the fresh v11 per-tenant goodput/parity lines
    # must carry the SLO side of the claim — a per-tenant throughput
    # without attainment cannot say whether that tenant's deadlines
    # held, and a parity ratio without its token counts cannot be
    # re-derived.
    if "tenant" in rec and (not isinstance(rec["tenant"], str)
                            or not rec["tenant"]):
        errs.append(f"'tenant' must be a non-empty string when "
                    f"present, got {rec['tenant']!r}")
    v11 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
           and sv_rec >= 11)
    if (v11 and isinstance(metric, str)
            and "error" not in rec and not rec.get("stale")):
        if "_tenant_" in metric and metric.endswith("_goodput"):
            if "tenant" not in rec:
                errs.append("fresh per-tenant goodput records must "
                            "carry 'tenant' (schema v11)")
            att = _need(rec, errs, "slo_attainment", numbers.Number,
                        allow_none=True)
            if (isinstance(att, numbers.Number)
                    and not isinstance(att, bool)
                    and not (0.0 <= att <= 1.0)):
                errs.append(f"'slo_attainment' must be null or in "
                            f"[0, 1], got {att!r}")
        if metric.endswith("_tenant_parity"):
            counts = {}
            for key in ("tenants_goodput_tokens", "tokens_within_slo"):
                v = _need(rec, errs, key, int)
                if isinstance(v, int) and not isinstance(v, bool):
                    if v < 0:
                        errs.append(f"{key!r} must be >= 0, got {v}")
                    else:
                        counts[key] = v
            val = rec.get("value")
            if (len(counts) == 2 and counts["tokens_within_slo"] > 0
                    and isinstance(val, numbers.Number)
                    and not isinstance(val, bool)):
                expect = (counts["tenants_goodput_tokens"]
                          / counts["tokens_within_slo"])
                if abs(val - expect) > 0.005:
                    errs.append(
                        f"value ({val}) inconsistent with "
                        f"tenants_goodput_tokens/tokens_within_slo "
                        f"({expect:.4g})")
    # QoS-tagged bench lines (bench.py --fleet QoS leg, schema v14):
    # whenever a line names a priority class it must name it
    # coherently; fresh v14 per-class goodput lines must carry the SLO
    # side of the claim, and the preemption-parity line must carry the
    # token counts its ratio came from plus the preemption count it
    # survived — an exactness claim that preempted nothing measured
    # nothing.
    if "qos_class" in rec and (not isinstance(rec["qos_class"], str)
                               or not rec["qos_class"]):
        errs.append(f"'qos_class' must be a non-empty string when "
                    f"present, got {rec['qos_class']!r}")
    v14 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
           and sv_rec >= 14)
    if (v14 and isinstance(metric, str)
            and "error" not in rec and not rec.get("stale")):
        if "_class_" in metric and metric.endswith("_goodput"):
            if "qos_class" not in rec:
                errs.append("fresh per-class goodput records must "
                            "carry 'qos_class' (schema v14)")
            att = _need(rec, errs, "slo_attainment", numbers.Number,
                        allow_none=True)
            if (isinstance(att, numbers.Number)
                    and not isinstance(att, bool)
                    and not (0.0 <= att <= 1.0)):
                errs.append(f"'slo_attainment' must be null or in "
                            f"[0, 1], got {att!r}")
        if metric.endswith("_preemption_parity"):
            counts = {}
            for key in ("matched_tokens", "expected_tokens"):
                v = _need(rec, errs, key, int)
                if isinstance(v, int) and not isinstance(v, bool):
                    if v < 0:
                        errs.append(f"{key!r} must be >= 0, got {v}")
                    else:
                        counts[key] = v
            pre = _need(rec, errs, "preemptions", int)
            if (isinstance(pre, int) and not isinstance(pre, bool)
                    and pre < 1):
                errs.append(f"'preemptions' must be >= 1 on a "
                            f"preemption-parity line, got {pre}")
            val = rec.get("value")
            if (len(counts) == 2 and counts["expected_tokens"] > 0
                    and isinstance(val, numbers.Number)
                    and not isinstance(val, bool)):
                expect = (counts["matched_tokens"]
                          / counts["expected_tokens"])
                if abs(val - expect) > 0.005:
                    errs.append(
                        f"value ({val}) inconsistent with "
                        f"matched_tokens/expected_tokens "
                        f"({expect:.4g})")
    # ZeRO-tagged bench lines (bench.py --comm zero legs, schema v15):
    # whenever a line names a ZeRO stage it must be a real one; fresh
    # v15 zero train-throughput lines must say WHICH stage produced the
    # number — trending a stage-3 rate against a stage-1 baseline
    # unknowingly is the blind spot the tag closes.
    if "zero_stage" in rec:
        zs = rec["zero_stage"]
        if not isinstance(zs, int) or isinstance(zs, bool) \
                or zs not in (1, 2, 3):
            errs.append(f"'zero_stage' must be 1, 2 or 3 when present, "
                        f"got {zs!r}")
    v15 = (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
           and sv_rec >= 15)
    if (v15 and isinstance(metric, str) and "zero" in metric
            and metric.endswith("_train_throughput")
            and "error" not in rec and not rec.get("stale")
            and "zero_stage" not in rec):
        errs.append("fresh ZeRO train-throughput records must carry "
                    "'zero_stage' (schema v15)")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


def validate_bench_jsonl(lines: Iterable[str]) -> List[str]:
    """Validate a bench stdout stream: every non-empty line must parse
    as JSON and pass the record schema."""
    return _validate_jsonl(lines, validate_bench_record)


# -- graph-lint record schema ---------------------------------------------

_LINT_SEVERITIES = ("error", "warning", "info")


def validate_lint_record(rec: Any) -> List[str]:
    """Schema check for one graph-lint JSONL record (what
    ``python -m apex_tpu.analysis`` and tests/ci/graph_lint.py emit):
    the common envelope (schema_version / host / stale) plus either a
    finding (``kind: graph_lint``) or the run summary
    (``kind: graph_lint_summary``)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types):
        return _need(rec, errs, key, types)

    _check_envelope(rec, errs)
    kind = rec.get("kind")
    if kind == "graph_lint":
        for key in ("rule", "entry_point", "message"):
            v = need(key, str)
            if isinstance(v, str) and not v:
                errs.append(f"{key!r} must be non-empty")
        sev = need("severity", str)
        if isinstance(sev, str) and sev not in _LINT_SEVERITIES:
            errs.append(f"severity must be one of {_LINT_SEVERITIES}, "
                        f"got {sev!r}")
        if "detail" in rec and not isinstance(rec["detail"], dict):
            errs.append("'detail' must be an object when present")
    elif kind == "graph_lint_summary":
        for key in ("entry_points", "rules", "findings", "errors",
                    "warnings"):
            v = need(key, int)
            if isinstance(v, int) and not isinstance(v, bool) and v < 0:
                errs.append(f"{key!r} must be >= 0, got {v}")
        f, e, w = (rec.get("findings"), rec.get("errors"),
                   rec.get("warnings"))
        if all(isinstance(v, int) for v in (f, e, w)) and f != e + w:
            errs.append(f"findings ({f}) != errors ({e}) + warnings ({w})")
    else:
        errs.append(f"unknown lint kind {kind!r}")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


# -- fleet record schema ---------------------------------------------------

# monotonic fleet totals every ``kind: fleet`` record must carry —
# Fleet.record() emits exactly these (plus replicas/policy/state tallies)
_FLEET_COUNTS = ("queue_depth", "submitted", "finished", "failed",
                 "shed", "retries", "failovers", "drains", "tokens")

# the per-tenant bucket tallies a v11 ``tenants`` block carries —
# the stdlib-side duplicate of fleet.slo's tenant bucket (this module
# must stay importable without jax; tests pin the shapes equal).
# Every field is a non-negative int; ``slo_attainment`` /
# ``goodput_tokens_per_s`` ride alongside with the fleet-level
# contract (null-or-fraction / non-negative number).
TENANT_COUNTS = ("submitted", "finished", "failed", "shed",
                 "deadline_exceeded", "slo_misses", "goodput_tokens",
                 "with_deadline", "within_deadline")

# the per-class bucket tallies a v14 ``classes`` block carries — the
# tenant bucket plus ``preempted`` (requests evicted mid-decode to
# admit a higher-priority class; the evictee is re-queued from its
# prompt, so ``preempted`` is not a failure count).  Stdlib-side
# duplicate of fleet.slo's class bucket; tests pin the shapes equal.
CLASS_COUNTS = TENANT_COUNTS + ("preempted",)


def _check_tenants_block(rec, errs):
    """The v11 per-tenant rollup contract, validated whenever present:
    ``tenants`` maps non-empty tenant names to buckets of TENANT_COUNTS
    tallies (ints >= 0, internally consistent — finishes cannot exceed
    submissions, within-deadline is a subset of with-deadline), and the
    per-tenant sums stay within the fleet totals (untagged requests are
    counted fleet-wide but deliberately kept OUT of the tenant map, so
    the sums are <=, never ==)."""
    if "tenants_dropped" in rec:
        v = rec["tenants_dropped"]
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"'tenants_dropped' must be an int >= 0, "
                        f"got {v!r}")
    if "tenants" not in rec:
        return
    tenants = rec["tenants"]
    if not isinstance(tenants, dict):
        errs.append("'tenants' must be an object when present")
        return
    sums = {k: 0 for k in ("shed", "deadline_exceeded",
                           "goodput_tokens")}
    for name, b in tenants.items():
        if not isinstance(name, str) or not name:
            errs.append(f"tenant names must be non-empty strings, "
                        f"got {name!r}")
        if not isinstance(b, dict):
            errs.append(f"tenants[{name!r}] must be an object")
            continue
        for key in TENANT_COUNTS:
            v = b.get(key)
            if key not in b:
                errs.append(f"tenants[{name!r}] missing {key!r}")
            elif not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"tenants[{name!r}].{key} must be an int "
                            f">= 0, got {v!r}")
            elif key in sums:
                sums[key] += v
        fin, sub = b.get("finished"), b.get("submitted")
        if (isinstance(fin, int) and isinstance(sub, int)
                and not isinstance(fin, bool)
                and not isinstance(sub, bool) and fin > sub):
            errs.append(f"tenants[{name!r}]: finished ({fin}) exceeds "
                        f"submitted ({sub})")
        wi, wd = b.get("within_deadline"), b.get("with_deadline")
        if (isinstance(wi, int) and isinstance(wd, int)
                and not isinstance(wi, bool)
                and not isinstance(wd, bool) and wi > wd):
            errs.append(f"tenants[{name!r}]: within_deadline ({wi}) "
                        f"exceeds with_deadline ({wd})")
        att = b.get("slo_attainment")
        if att is not None and (
                not isinstance(att, numbers.Number)
                or isinstance(att, bool)
                or not (0.0 <= att <= 1.0)):
            errs.append(f"tenants[{name!r}].slo_attainment must be "
                        f"null or in [0, 1], got {att!r}")
        gp = b.get("goodput_tokens_per_s")
        if gp is not None and (
                not isinstance(gp, numbers.Number)
                or isinstance(gp, bool) or not (gp >= 0)):
            errs.append(f"tenants[{name!r}].goodput_tokens_per_s must "
                        f"be null or a number >= 0, got {gp!r}")
    # untagged traffic keeps the tenant sums strictly within the fleet
    # totals; a sum EXCEEDING its total is double-counting
    for key, total_key in (("shed", "shed"),
                           ("deadline_exceeded", "deadline_exceeded"),
                           ("goodput_tokens", "tokens_within_slo")):
        total = rec.get(total_key)
        if (isinstance(total, int) and not isinstance(total, bool)
                and sums[key] > total):
            errs.append(f"sum of per-tenant {key} ({sums[key]}) "
                        f"exceeds fleet {total_key} ({total})")


def _check_classes_block(rec, errs):
    """The v14 per-class rollup contract, validated whenever present:
    ``classes`` maps non-empty priority-class names to buckets of
    CLASS_COUNTS tallies (ints >= 0, internally consistent the tenant
    way), each riding with the SLO pair (null-or-fraction attainment,
    non-negative goodput rate) and the live queue shape (depth/cap
    ints, weight >= 1, preemptible bool) — and the per-class sums stay
    within the fleet totals (every admitted request resolves to
    exactly one class, so under a multi-class policy the sums may
    reach the totals but never exceed them).  ``preemptions`` is the
    fleet-level eviction total the per-class ``preempted`` tallies
    roll up into."""
    if "preemptions" in rec:
        v = rec["preemptions"]
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"'preemptions' must be an int >= 0, "
                        f"got {v!r}")
    if "classes" not in rec:
        return
    classes = rec["classes"]
    if not isinstance(classes, dict):
        errs.append("'classes' must be an object when present")
        return
    sums = {k: 0 for k in ("shed", "deadline_exceeded",
                           "goodput_tokens")}
    preempted_sum = 0
    for name, b in classes.items():
        if not isinstance(name, str) or not name:
            errs.append(f"class names must be non-empty strings, "
                        f"got {name!r}")
        if not isinstance(b, dict):
            errs.append(f"classes[{name!r}] must be an object")
            continue
        for key in CLASS_COUNTS:
            v = b.get(key)
            if key not in b:
                errs.append(f"classes[{name!r}] missing {key!r}")
            elif not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"classes[{name!r}].{key} must be an int "
                            f">= 0, got {v!r}")
            elif key in sums:
                sums[key] += v
            elif key == "preempted":
                preempted_sum += v
        fin, sub = b.get("finished"), b.get("submitted")
        if (isinstance(fin, int) and isinstance(sub, int)
                and not isinstance(fin, bool)
                and not isinstance(sub, bool) and fin > sub):
            errs.append(f"classes[{name!r}]: finished ({fin}) exceeds "
                        f"submitted ({sub})")
        wi, wd = b.get("within_deadline"), b.get("with_deadline")
        if (isinstance(wi, int) and isinstance(wd, int)
                and not isinstance(wi, bool)
                and not isinstance(wd, bool) and wi > wd):
            errs.append(f"classes[{name!r}]: within_deadline ({wi}) "
                        f"exceeds with_deadline ({wd})")
        att = b.get("slo_attainment")
        if att is not None and (
                not isinstance(att, numbers.Number)
                or isinstance(att, bool)
                or not (0.0 <= att <= 1.0)):
            errs.append(f"classes[{name!r}].slo_attainment must be "
                        f"null or in [0, 1], got {att!r}")
        gp = b.get("goodput_tokens_per_s")
        if gp is not None and (
                not isinstance(gp, numbers.Number)
                or isinstance(gp, bool) or not (gp >= 0)):
            errs.append(f"classes[{name!r}].goodput_tokens_per_s must "
                        f"be null or a number >= 0, got {gp!r}")
        for key in ("queue_depth", "queue_cap"):
            if key in b:
                v = b[key]
                if (not isinstance(v, int) or isinstance(v, bool)
                        or v < 0):
                    errs.append(f"classes[{name!r}].{key} must be an "
                                f"int >= 0 when present, got {v!r}")
        if "weight" in b:
            w = b["weight"]
            if not isinstance(w, int) or isinstance(w, bool) or w < 1:
                errs.append(f"classes[{name!r}].weight must be an int "
                            f">= 1 when present, got {w!r}")
        if "preemptible" in b and not isinstance(b["preemptible"],
                                                 bool):
            errs.append(f"classes[{name!r}].preemptible must be a "
                        f"bool when present, got "
                        f"{b['preemptible']!r}")
    for key, total_key in (("shed", "shed"),
                           ("deadline_exceeded", "deadline_exceeded"),
                           ("goodput_tokens", "tokens_within_slo")):
        total = rec.get(total_key)
        if (isinstance(total, int) and not isinstance(total, bool)
                and sums[key] > total):
            errs.append(f"sum of per-class {key} ({sums[key]}) "
                        f"exceeds fleet {total_key} ({total})")
    pre = rec.get("preemptions")
    if (isinstance(pre, int) and not isinstance(pre, bool)
            and preempted_sum > pre):
        errs.append(f"sum of per-class preempted ({preempted_sum}) "
                    f"exceeds fleet preemptions ({pre})")


def validate_fleet_record(rec: Any) -> List[str]:
    """Schema check for one ``kind: fleet`` JSONL record
    (``Fleet.record()`` enriched by the exporter): the common envelope
    plus the replica/state tallies and the fleet counters
    (shed/retries/failovers/drains & co), with the cross-field sanity
    checks a dashboard would otherwise discover at 3am — state tallies
    cannot exceed the replica count, finishes cannot exceed
    submissions."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types):
        return _need(rec, errs, key, types)

    _check_envelope(rec, errs)
    if rec.get("kind") != "fleet":
        errs.append(f"kind must be 'fleet', got {rec.get('kind')!r}")
    # the flight-recorder cross-reference: every fleet snapshot names
    # the fleet-run trace whose request traces (``kind: trace``,
    # trace_id "<fleet>/r<rid>") it aggregates — a dashboard can join
    # the two streams on this id.  Schema v2 requirement: archived v1
    # fleet records (pre-flight-recorder) predate the field and stay
    # valid at their declared version.
    sv = rec.get("schema_version", SCHEMA_VERSION)
    if isinstance(sv, int) and not isinstance(sv, bool) and sv >= 2:
        # (a non-int schema_version is already an envelope error — no
        # crash, no v2 requirements)
        tid = need("trace_id", str)
        if isinstance(tid, str) and not tid:
            errs.append("trace_id must be non-empty")
    pol = need("policy", str)
    if isinstance(pol, str) and not pol:
        errs.append("policy must be non-empty")
    n = need("replicas", int)
    if isinstance(n, int) and not isinstance(n, bool) and n < 1:
        errs.append(f"replicas must be >= 1, got {n}")
    tally = 0
    for key in ("healthy", "degraded", "dead"):
        v = need(key, int)
        if isinstance(v, int) and not isinstance(v, bool):
            if v < 0:
                errs.append(f"{key!r} must be >= 0, got {v}")
            tally += v
    if isinstance(n, int) and not isinstance(n, bool) and tally > n:
        errs.append(f"healthy+degraded+dead ({tally}) exceeds "
                    f"replicas ({n})")
    for key in _FLEET_COUNTS:
        v = need(key, int)
        if isinstance(v, int) and not isinstance(v, bool) and v < 0:
            errs.append(f"{key!r} must be >= 0, got {v}")
    fin, sub = rec.get("finished"), rec.get("submitted")
    if (isinstance(fin, int) and isinstance(sub, int)
            and not isinstance(fin, bool) and not isinstance(sub, bool)
            and fin > sub):
        errs.append(f"finished ({fin}) exceeds submitted ({sub})")
    # SLO / goodput / deadline-sweep fields (schema v5 additions,
    # OPTIONAL at every version — older records simply predate them,
    # but whenever present they must be internally consistent: goodput
    # cannot exceed total tokens, attainment is a fraction or null,
    # and the deadline-sweep aggregate mirrors what the flight ring's
    # ``deadline_exceeded`` events carry)
    for opt in ("deadline_exceeded", "tokens_within_slo"):
        if opt in rec:
            v = rec[opt]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{opt!r} must be an int >= 0 when "
                            f"present, got {v!r}")
    tw, tok = rec.get("tokens_within_slo"), rec.get("tokens")
    if (isinstance(tw, int) and isinstance(tok, int)
            and not isinstance(tw, bool) and not isinstance(tok, bool)
            and tw > tok):
        errs.append(f"tokens_within_slo ({tw}) exceeds tokens ({tok})")
    if "goodput_tokens_per_s" in rec:
        v = rec["goodput_tokens_per_s"]
        if (not isinstance(v, numbers.Number) or isinstance(v, bool)
                or not (v >= 0)):
            errs.append(f"'goodput_tokens_per_s' must be a number "
                        f">= 0 when present, got {v!r}")
    if "slo_attainment" in rec and rec["slo_attainment"] is not None:
        v = rec["slo_attainment"]
        if (not isinstance(v, numbers.Number) or isinstance(v, bool)
                or not (0.0 <= v <= 1.0)):
            errs.append(f"'slo_attainment' must be null or in [0, 1], "
                        f"got {v!r}")
    if "mttr" in rec:
        # schema-v6 optional: the fleet's failover→first-progress
        # aggregate ({last, mean, count}), same nullability contract
        # as the recovery record's mttr_s
        mttr = rec["mttr"]
        if not isinstance(mttr, dict):
            errs.append("'mttr' must be an object when present")
        else:
            c = mttr.get("count")
            if not isinstance(c, int) or isinstance(c, bool) or c < 0:
                errs.append(f"mttr.count must be an int >= 0, got "
                            f"{c!r}")
            for k in ("last", "mean"):
                v = mttr.get(k)
                if v is None:
                    continue
                if (not isinstance(v, numbers.Number)
                        or isinstance(v, bool) or v != v
                        or not (v >= 0)):
                    errs.append(f"mttr.{k} must be null or a finite "
                                f"number >= 0, got {v!r}")
    # the v11 tenant plane: validated whenever present, required on
    # records declaring v11 — Fleet.record() always emits the block
    # (empty object when no request was tagged), so a fresh record
    # missing it was hand-built
    if isinstance(sv, int) and not isinstance(sv, bool) and sv >= 11:
        if "tenants" not in rec:
            errs.append("fresh fleet records must carry 'tenants' "
                        "(schema v11: the per-tenant SLO rollup)")
        if "tenants_dropped" not in rec:
            errs.append("fresh fleet records must carry "
                        "'tenants_dropped' (schema v11)")
    _check_tenants_block(rec, errs)
    # the v14 QoS plane: validated whenever present, required on
    # records declaring v14 — Fleet.record() always emits the block
    # (zero buckets for every policy class when nothing ran), so a
    # fresh record missing it was hand-built
    if isinstance(sv, int) and not isinstance(sv, bool) and sv >= 14:
        if "classes" not in rec:
            errs.append("fresh fleet records must carry 'classes' "
                        "(schema v14: the per-QoS-class SLO rollup)")
        if "preemptions" not in rec:
            errs.append("fresh fleet records must carry "
                        "'preemptions' (schema v14)")
    _check_classes_block(rec, errs)
    if "deadline_last_sweep" in rec:
        sweep = rec["deadline_last_sweep"]
        if not isinstance(sweep, dict):
            errs.append("'deadline_last_sweep' must be an object when "
                        "present")
        else:
            c = sweep.get("count")
            if not isinstance(c, int) or isinstance(c, bool) or c < 0:
                errs.append(f"deadline_last_sweep.count must be an "
                            f"int >= 0, got {c!r}")
            rids = sweep.get("rids")
            if not isinstance(rids, list) or any(
                    not isinstance(r, int) or isinstance(r, bool)
                    for r in rids):
                errs.append("deadline_last_sweep.rids must be a list "
                            "of ints")
            elif isinstance(c, int) and not isinstance(c, bool) \
                    and len(rids) > c:
                errs.append(f"deadline_last_sweep lists {len(rids)} "
                            f"rids for a count of {c}")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


# -- memory record schema ---------------------------------------------------

# Compiled.memory_analysis() components every ``kind: memory`` record
# must carry; ``peak_bytes`` must reassemble from them exactly.
# Public: observability.memory builds its plans from THIS tuple, so
# the producer and the validator cannot drift.  (This module stays
# import-light — memory.py imports from here, never the reverse, so
# tests/ci/check_bench_schema.py's jax-free loader keeps working.)
MEMORY_PLAN_KEYS = ("argument_bytes", "output_bytes", "temp_bytes",
                    "alias_bytes", "generated_code_bytes")
_MEMORY_PLAN_KEYS = MEMORY_PLAN_KEYS


def validate_memory_record(rec: Any) -> List[str]:
    """Schema check for one ``kind: memory`` JSONL record (the
    cost-model/memory-plan dump emitted per analysis entry point by
    ``python -m apex_tpu.analysis --memory`` and per bench config by
    ``bench.py``): the common envelope, a subject (``entry_point`` or
    ``metric``), non-negative analytic FLOP/byte totals, the compiled
    memory-plan components, and the arithmetic cross-check — a
    ``peak_bytes`` that does not reassemble from its own components is
    a hand-built record, not a plan."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types):
        return _need(rec, errs, key, types)

    _check_envelope(rec, errs)
    if rec.get("kind") != "memory":
        errs.append(f"kind must be 'memory', got {rec.get('kind')!r}")
    subject = rec.get("entry_point", rec.get("metric"))
    if not isinstance(subject, str) or not subject:
        errs.append("memory records must carry a non-empty "
                    "'entry_point' or 'metric'")
    for key in ("flops", "transcendentals", "matmul_flops"):
        v = need(key, numbers.Number)
        if (isinstance(v, numbers.Number) and not isinstance(v, bool)
                and v < 0):
            errs.append(f"{key!r} must be >= 0, got {v}")
    parts = {}
    for key in _MEMORY_PLAN_KEYS + ("peak_bytes", "bytes_accessed"):
        v = need(key, int)
        if isinstance(v, int) and not isinstance(v, bool):
            if v < 0:
                errs.append(f"{key!r} must be >= 0, got {v}")
            parts[key] = v
    if len(parts) == len(_MEMORY_PLAN_KEYS) + 2:
        expect = (parts["argument_bytes"] + parts["output_bytes"]
                  + parts["temp_bytes"] + parts["generated_code_bytes"]
                  - parts["alias_bytes"])
        if parts["peak_bytes"] != expect:
            errs.append(
                f"peak_bytes ({parts['peak_bytes']}) != argument + "
                f"output + temp + generated_code - alias ({expect})")
    for opt in ("analytic_live_bytes", "analytic_temp_bytes",
                "kv_cache_bytes"):
        if opt in rec:
            v = rec[opt]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{opt!r} must be an int >= 0 when "
                            f"present, got {v!r}")
    for opt in ("matmul_flops_by_dtype", "bytes_by_dtype",
                "analytic_temp_bytes_by_dtype"):
        if opt in rec and not isinstance(rec[opt], dict):
            errs.append(f"{opt!r} must be an object when present")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


# -- sharding record schema -------------------------------------------------

def validate_sharding_record(rec: Any) -> List[str]:
    """Schema check for one ``kind: sharding`` JSONL record (the static
    replication ledger from ``analysis.sharding.
    entry_point_sharding_record``, schema v13): the common envelope, a
    non-empty ``entry_point``, a coherent mesh (``world`` equals the
    product of ``mesh_axes``), non-negative byte totals with the
    arithmetic identity ``unique_bytes + replicated_bytes == world *
    argument_bytes`` (the ledger must reassemble from its own parts),
    a per-dtype split that sums to ``replicated_bytes``, a
    ``replicated_fraction`` consistent with the totals, well-formed
    ``top_replicated`` entries, and a resharding census of
    non-negative eqn counts."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types):
        return _need(rec, errs, key, types)

    _check_envelope(rec, errs)
    if rec.get("kind") != "sharding":
        errs.append(f"kind must be 'sharding', got {rec.get('kind')!r}")
    epn = need("entry_point", str)
    if isinstance(epn, str) and not epn:
        errs.append("entry_point must be non-empty")
    src = need("source", str)
    if isinstance(src, str) and not src:
        errs.append("source must be non-empty")
    world = need("world", int)
    if isinstance(world, int) and not isinstance(world, bool) \
            and world < 1:
        errs.append(f"world must be >= 1, got {world}")
    axes = need("mesh_axes", dict)
    if isinstance(axes, dict):
        prod = 1
        ok = bool(axes)
        for name, sz in axes.items():
            if not isinstance(name, str) or not name:
                errs.append(f"mesh axis names must be non-empty "
                            f"strings, got {name!r}")
                ok = False
            if not isinstance(sz, int) or isinstance(sz, bool) or sz < 1:
                errs.append(f"mesh_axes[{name!r}] must be an int >= 1, "
                            f"got {sz!r}")
                ok = False
            else:
                prod *= sz
        if not axes:
            errs.append("mesh_axes must be non-empty")
        if (ok and isinstance(world, int) and not isinstance(world, bool)
                and prod != world):
            errs.append(f"world ({world}) != product of mesh_axes "
                        f"({prod})")
    sm = need("shard_maps", int)
    if isinstance(sm, int) and not isinstance(sm, bool) and sm < 1:
        errs.append(f"shard_maps must be >= 1, got {sm}")
    parts = {}
    for key in ("argument_bytes", "unique_bytes", "replicated_bytes"):
        v = need(key, int)
        if isinstance(v, int) and not isinstance(v, bool):
            if v < 0:
                errs.append(f"{key!r} must be >= 0, got {v}")
            else:
                parts[key] = v
    if (len(parts) == 3 and isinstance(world, int)
            and not isinstance(world, bool) and world >= 1
            and parts["unique_bytes"] + parts["replicated_bytes"]
            != world * parts["argument_bytes"]):
        errs.append(
            f"unique_bytes + replicated_bytes "
            f"({parts['unique_bytes']} + {parts['replicated_bytes']}) "
            f"!= world * argument_bytes "
            f"({world} * {parts['argument_bytes']}) — the ledger must "
            f"reassemble from its own parts")
    by_dtype = need("replicated_bytes_by_dtype", dict)
    if isinstance(by_dtype, dict):
        total = 0
        ok = True
        for dt, v in by_dtype.items():
            if not isinstance(dt, str) or not dt:
                errs.append(f"replicated_bytes_by_dtype keys must be "
                            f"non-empty strings, got {dt!r}")
                ok = False
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"replicated_bytes_by_dtype[{dt!r}] must "
                            f"be an int >= 0, got {v!r}")
                ok = False
            else:
                total += v
        if ok and "replicated_bytes" in parts \
                and total != parts["replicated_bytes"]:
            errs.append(f"replicated_bytes_by_dtype sums to {total}, "
                        f"!= replicated_bytes "
                        f"({parts['replicated_bytes']})")
    frac = need("replicated_fraction", numbers.Number)
    if (isinstance(frac, numbers.Number) and not isinstance(frac, bool)
            and not (0.0 <= frac <= 1.0)):
        errs.append(f"replicated_fraction must be in [0, 1], got "
                    f"{frac!r}")
    if (isinstance(frac, numbers.Number) and not isinstance(frac, bool)
            and len(parts) == 3 and isinstance(world, int)
            and not isinstance(world, bool) and world >= 1
            and parts["argument_bytes"] > 0):
        expect = (parts["replicated_bytes"]
                  / (world * parts["argument_bytes"]))
        if abs(frac - expect) > 1e-9:
            errs.append(f"replicated_fraction ({frac}) inconsistent "
                        f"with replicated_bytes / (world * "
                        f"argument_bytes) ({expect:.6g})")
    top = need("top_replicated", list)
    if isinstance(top, list):
        for i, t in enumerate(top):
            if not isinstance(t, dict):
                errs.append(f"top_replicated[{i}] is not an object")
                continue
            idx = t.get("index")
            if not isinstance(idx, int) or isinstance(idx, bool) \
                    or idx < 0:
                errs.append(f"top_replicated[{i}].index must be an "
                            f"int >= 0, got {idx!r}")
            if not isinstance(t.get("shape"), list):
                errs.append(f"top_replicated[{i}].shape must be a list")
            if not isinstance(t.get("dtype"), str) or not t.get("dtype"):
                errs.append(f"top_replicated[{i}].dtype must be a "
                            f"non-empty string")
            lb = t.get("local_bytes")
            if not isinstance(lb, int) or isinstance(lb, bool) or lb < 0:
                errs.append(f"top_replicated[{i}].local_bytes must be "
                            f"an int >= 0, got {lb!r}")
            rf = t.get("replication_factor")
            if (not isinstance(rf, numbers.Number)
                    or isinstance(rf, bool) or not (rf >= 1)):
                errs.append(f"top_replicated[{i}].replication_factor "
                            f"must be a number >= 1, got {rf!r}")
            if not isinstance(t.get("spec"), str) or not t.get("spec"):
                errs.append(f"top_replicated[{i}].spec must be a "
                            f"non-empty string")
    census = need("resharding_eqns", dict)
    if isinstance(census, dict):
        for prim, n in census.items():
            if not isinstance(prim, str) or not prim:
                errs.append(f"resharding_eqns keys must be non-empty "
                            f"strings, got {prim!r}")
            if not isinstance(n, int) or isinstance(n, bool) or n < 0:
                errs.append(f"resharding_eqns[{prim!r}] must be an "
                            f"int >= 0, got {n!r}")
    # v15: a ledger for a ZeRO entry point must say which stage it
    # measured — stage 3's collapse (nothing replicated but BN state
    # and scalars) is only comparable against stage 1/2 ledgers when
    # each carries its stage; validated whenever present at any
    # version, required on fresh v15 zero-EP records
    if "zero_stage" in rec:
        zs = rec["zero_stage"]
        if not isinstance(zs, int) or isinstance(zs, bool) \
                or zs not in (1, 2, 3):
            errs.append(f"'zero_stage' must be 1, 2 or 3 when present, "
                        f"got {zs!r}")
    sv_rec = rec.get("schema_version")
    if (isinstance(sv_rec, int) and not isinstance(sv_rec, bool)
            and sv_rec >= 15 and isinstance(epn, str) and "zero" in epn
            and not rec.get("stale") and "zero_stage" not in rec):
        errs.append("fresh sharding records for ZeRO entry points must "
                    "carry 'zero_stage' (schema v15)")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


# -- numerics record schema -------------------------------------------------

def validate_numerics_record(rec: Any) -> List[str]:
    """Schema check for one ``kind: numerics`` JSONL record
    (``NumericsMonitor.to_record`` enriched by the exporter): the
    common envelope, a subject (``metric`` or ``entry_point``), the
    step/overflow tallies, a non-empty per-layer health list
    (nonfinite counts, abs-max, grad norm, underflow fraction), a
    ``culprit`` that — when named — must actually be one of the
    record's layers (an attribution pointing at a layer the record
    does not describe is a hand-built record, not a flush), plus the
    optional per-bucket and divergence-digest sections with their own
    cross-field consistency (``in_sync`` iff zero desync steps)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types, allow_none=False):
        return _need(rec, errs, key, types, allow_none)

    _check_envelope(rec, errs)
    if rec.get("kind") != "numerics":
        errs.append(f"kind must be 'numerics', got {rec.get('kind')!r}")
    subject = rec.get("entry_point", rec.get("metric"))
    if not isinstance(subject, str) or not subject:
        errs.append("numerics records must carry a non-empty "
                    "'entry_point' or 'metric'")
    steps = need("steps", int)
    ov = need("overflow_steps", int)
    for key, v in (("steps", steps), ("overflow_steps", ov)):
        if isinstance(v, int) and not isinstance(v, bool) and v < 0:
            errs.append(f"{key!r} must be >= 0, got {v}")
    if (isinstance(steps, int) and isinstance(ov, int)
            and not isinstance(steps, bool) and not isinstance(ov, bool)
            and ov > steps):
        errs.append(f"overflow_steps ({ov}) exceeds steps ({steps})")
    for opt in ("loss_scale", "grad_norm", "tiny"):
        if opt in rec:
            v = rec[opt]
            if (not isinstance(v, numbers.Number)
                    or isinstance(v, bool) or v < 0):
                errs.append(f"{opt!r} must be a number >= 0 when "
                            f"present, got {v!r}")
    if "half_dtype" in rec and rec["half_dtype"] not in (
            "float16", "bfloat16"):
        errs.append(f"'half_dtype' must be float16/bfloat16, got "
                    f"{rec['half_dtype']!r}")
    layer_names = set()
    layers = need("layers", list)
    if isinstance(layers, list):
        if not layers:
            errs.append("layers must be non-empty (a health record "
                        "with no layers describes nothing)")
        for i, lyr in enumerate(layers):
            if not isinstance(lyr, dict):
                errs.append(f"layers[{i}] is not an object")
                continue
            name = lyr.get("name")
            if not isinstance(name, str) or not name:
                errs.append(f"layers[{i}].name must be a non-empty "
                            f"string")
            else:
                layer_names.add(name)
            nf = lyr.get("nonfinite")
            if not isinstance(nf, int) or isinstance(nf, bool) or nf < 0:
                errs.append(f"layers[{i}].nonfinite must be an int "
                            f">= 0, got {nf!r}")
            for key in ("abs_max", "grad_norm"):
                v = lyr.get(key)
                # `not (v >= 0)` also rejects NaN (all NaN
                # comparisons are false) — a health record carrying
                # un-numbers is worse than none
                if (not isinstance(v, numbers.Number)
                        or isinstance(v, bool) or not (v >= 0)):
                    errs.append(f"layers[{i}].{key} must be a number "
                                f">= 0, got {v!r}")
            uf = lyr.get("underflow_fraction")
            if (not isinstance(uf, numbers.Number)
                    or isinstance(uf, bool)
                    or not (0.0 <= uf <= 1.0)):
                errs.append(f"layers[{i}].underflow_fraction must be "
                            f"in [0, 1], got {uf!r}")
    culprit = rec.get("culprit")
    if culprit is not None:
        if not isinstance(culprit, str) or not culprit:
            errs.append(f"'culprit' must be null or a non-empty "
                        f"string, got {culprit!r}")
        elif isinstance(layers, list) and culprit not in layer_names:
            errs.append(f"culprit {culprit!r} is not one of the "
                        f"record's layers")
    if culprit is not None and isinstance(ov, int) \
            and not isinstance(ov, bool) and ov == 0:
        errs.append("a culprit with zero overflow_steps attributes an "
                    "overflow that never happened")
    if "buckets" in rec:
        bks = rec["buckets"]
        if not isinstance(bks, list):
            errs.append("'buckets' must be a list when present")
        else:
            for i, b in enumerate(bks):
                if not isinstance(b, dict):
                    errs.append(f"buckets[{i}] is not an object")
                    continue
                lbl = b.get("label")
                if not isinstance(lbl, str) or not lbl:
                    errs.append(f"buckets[{i}].label must be a "
                                f"non-empty string")
                nf = b.get("nonfinite")
                if not isinstance(nf, int) or isinstance(nf, bool) \
                        or nf < 0:
                    errs.append(f"buckets[{i}].nonfinite must be an "
                                f"int >= 0, got {nf!r}")
                for key in ("abs_max", "grad_norm",
                            "compression_sq_error"):
                    if key in b:
                        v = b[key]
                        if (not isinstance(v, numbers.Number)
                                or isinstance(v, bool)
                                or not (v >= 0)):
                            errs.append(f"buckets[{i}].{key} must be "
                                        f"a number >= 0, got {v!r}")
    if "divergence" in rec:
        div = rec["divergence"]
        if not isinstance(div, dict):
            errs.append("'divergence' must be an object when present")
        else:
            mr = div.get("max_rel_dev")
            if (not isinstance(mr, numbers.Number)
                    or isinstance(mr, bool) or not (mr >= 0)):
                errs.append(f"divergence.max_rel_dev must be a number "
                            f">= 0, got {mr!r}")
            ds = div.get("desync_steps")
            if not isinstance(ds, int) or isinstance(ds, bool) or ds < 0:
                errs.append(f"divergence.desync_steps must be an int "
                            f">= 0, got {ds!r}")
            ins = div.get("in_sync")
            if not isinstance(ins, bool):
                errs.append(f"divergence.in_sync must be a bool, got "
                            f"{ins!r}")
            elif isinstance(ds, int) and not isinstance(ds, bool) \
                    and ins != (ds == 0):
                errs.append(f"divergence.in_sync ({ins}) inconsistent "
                            f"with desync_steps ({ds})")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


# -- run record schema ------------------------------------------------------

# anomaly kinds a supervisor may declare — kept in sync with
# observability.supervisor.ANOMALY_KINDS (duplicated here so the
# stdlib-only CI loader never imports the supervisor module; the
# pytest coverage pins the two tuples equal)
RUN_ANOMALY_KINDS = ("stall", "loss_spike", "nan",
                     "throughput_regression", "replica_divergence",
                     "recompilation_storm")


def validate_run_record(rec: Any) -> List[str]:
    """Schema check for one ``kind: run`` JSONL record
    (``RunSupervisor.record`` enriched by the exporter, schema v5):
    the common envelope, a non-empty ``run`` name, the observation /
    watermark tallies, per-kind anomaly counts over the KNOWN kinds,
    a bounded anomaly-detail list whose entries each name a counted
    kind, and the verdict cross-check — ``ok`` iff zero anomalies
    (a record claiming health while counting anomalies is lying to
    the dashboard)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types, allow_none=False):
        return _need(rec, errs, key, types, allow_none)

    _check_envelope(rec, errs)
    if rec.get("kind") != "run":
        errs.append(f"kind must be 'run', got {rec.get('kind')!r}")
    run = need("run", str)
    if isinstance(run, str) and not run:
        errs.append("run must be non-empty")
    obs = need("observations", int)
    if isinstance(obs, int) and not isinstance(obs, bool) and obs < 0:
        errs.append(f"observations must be >= 0, got {obs}")
    wm = rec.get("watermark")
    if wm is not None and (not isinstance(wm, int)
                           or isinstance(wm, bool)):
        errs.append(f"'watermark' must be null or an int, got {wm!r}")
    verdict = need("verdict", str)
    if isinstance(verdict, str) and verdict not in ("ok", "attention"):
        errs.append(f"verdict must be 'ok' or 'attention', got "
                    f"{verdict!r}")
    counts = need("anomaly_counts", dict)
    total = None
    if isinstance(counts, dict):
        total = 0
        for k, v in sorted(counts.items()):
            if k not in RUN_ANOMALY_KINDS:
                errs.append(f"anomaly_counts names unknown kind {k!r} "
                            f"(known: {RUN_ANOMALY_KINDS})")
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"anomaly_counts[{k!r}] must be an int "
                            f">= 0, got {v!r}")
            else:
                total += v
    if isinstance(verdict, str) and total is not None \
            and verdict in ("ok", "attention") \
            and (verdict == "ok") != (total == 0):
        errs.append(f"verdict {verdict!r} inconsistent with "
                    f"{total} counted anomalies")
    anomalies = need("anomalies", list)
    if isinstance(anomalies, list):
        per_kind: Dict[str, int] = {}
        for i, a in enumerate(anomalies):
            if not isinstance(a, dict):
                errs.append(f"anomalies[{i}] is not an object")
                continue
            k = a.get("kind")
            if k not in RUN_ANOMALY_KINDS:
                errs.append(f"anomalies[{i}].kind must be one of "
                            f"{RUN_ANOMALY_KINDS}, got {k!r}")
            else:
                per_kind[k] = per_kind.get(k, 0) + 1
            o = a.get("observation")
            if not isinstance(o, int) or isinstance(o, bool) or o < 1:
                errs.append(f"anomalies[{i}].observation must be an "
                            f"int >= 1, got {o!r}")
        if isinstance(counts, dict):
            for k, n in sorted(per_kind.items()):
                c = counts.get(k)
                if isinstance(c, int) and not isinstance(c, bool) \
                        and n > c:
                    errs.append(
                        f"anomalies lists {n} {k!r} entries but "
                        f"anomaly_counts[{k!r}] is {c} (the detail "
                        f"list is bounded, the counts are exact — "
                        f"details can never exceed the count)")
    # the loss / step-time summaries, when present, must be objects of
    # numbers-or-null with NaN rejected (x == x is False only for NaN)
    for opt in ("loss", "step_time_s"):
        if opt in rec:
            d = rec[opt]
            if not isinstance(d, dict):
                errs.append(f"{opt!r} must be an object when present")
                continue
            for k, v in sorted(d.items()):
                if v is None:
                    continue
                if (not isinstance(v, numbers.Number)
                        or isinstance(v, bool) or v != v):
                    errs.append(f"{opt}.{k} must be a finite number "
                                f"or null, got {v!r}")
    for opt in ("checkpoints",):
        if opt in rec:
            v = rec[opt]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{opt!r} must be an int >= 0 when "
                            f"present, got {v!r}")
    if "duration_s" in rec:
        v = rec["duration_s"]
        if (not isinstance(v, numbers.Number) or isinstance(v, bool)
                or not (v >= 0)):
            errs.append(f"'duration_s' must be a number >= 0, got "
                        f"{v!r}")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


# -- recovery record schema -------------------------------------------------

# fleet.recovery.RECOVERY_ROLES / RECOVERY_ACTION_KINDS /
# RECOVERY_CAUSES (duplicated here so the stdlib-side validator needs
# no jax-adjacent import — tests pin the pairs equal, the
# RUN_ANOMALY_KINDS discipline)
RECOVERY_ROLES = ("training", "serving")
RECOVERY_ACTION_KINDS = (
    "world_shrink", "resume", "rollback", "preempt_snapshot",
    "admission_tighten", "admission_relax",
    "class_admission_tighten", "class_admission_relax",
    "window_shrink", "window_grow",
    "drain", "undrain",
    "cooldown_shorten", "cooldown_extend")
RECOVERY_CAUSES = ("fault", "verdict", "preemption")


def validate_recovery_record(rec: Any) -> List[str]:
    """Schema check for one ``kind: recovery`` JSONL record
    (``fleet.recovery.RecoveryLog.record`` enriched by the exporter,
    schema v6): the common envelope, a known controller ``role``, the
    episode/action tallies, a bounded action-detail list whose entries
    each name a known action kind inside a counted episode, and the
    MTTR aggregate — internally consistent the way a dashboard
    assumes (details never exceed the total, the per-episode maximum
    never exceeds it either, MTTR numbers are finite and
    non-negative)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types, allow_none=False):
        return _need(rec, errs, key, types, allow_none)

    _check_envelope(rec, errs)
    if rec.get("kind") != "recovery":
        errs.append(f"kind must be 'recovery', got {rec.get('kind')!r}")
    role = need("role", str)
    if isinstance(role, str) and role not in RECOVERY_ROLES:
        errs.append(f"role must be one of {RECOVERY_ROLES}, got "
                    f"{role!r}")
    subj = need("subject", str)
    if isinstance(subj, str) and not subj:
        errs.append("subject must be non-empty")
    eps = need("episodes", int)
    if isinstance(eps, int) and not isinstance(eps, bool) and eps < 0:
        errs.append(f"episodes must be >= 0, got {eps}")
    total = need("actions_total", int)
    if isinstance(total, int) and not isinstance(total, bool) \
            and total < 0:
        errs.append(f"actions_total must be >= 0, got {total}")
    mx = need("max_actions_in_episode", int)
    if isinstance(mx, int) and not isinstance(mx, bool):
        if mx < 0:
            errs.append(f"max_actions_in_episode must be >= 0, got "
                        f"{mx}")
        elif isinstance(total, int) and not isinstance(total, bool) \
                and mx > total:
            errs.append(f"max_actions_in_episode ({mx}) exceeds "
                        f"actions_total ({total})")
        elif (isinstance(eps, int) and not isinstance(eps, bool)
              and eps == 0 and mx > 0):
            errs.append(f"max_actions_in_episode ({mx}) with zero "
                        f"episodes")
    need("in_flight", bool)
    actions = need("actions", list)
    if isinstance(actions, list):
        if isinstance(total, int) and not isinstance(total, bool) \
                and len(actions) > total:
            errs.append(f"actions lists {len(actions)} entries but "
                        f"actions_total is {total} (the detail list "
                        f"is bounded, the counts are exact)")
        for i, a in enumerate(actions):
            if not isinstance(a, dict):
                errs.append(f"actions[{i}] is not an object")
                continue
            k = a.get("kind")
            if k not in RECOVERY_ACTION_KINDS:
                errs.append(f"actions[{i}].kind must be one of "
                            f"{RECOVERY_ACTION_KINDS}, got {k!r}")
            ep = a.get("episode")
            if ep is None:
                # an action taken before any episode opened (the
                # unwinding/correction case) carries a null episode
                pass
            elif not isinstance(ep, int) or isinstance(ep, bool) \
                    or ep < 1:
                errs.append(f"actions[{i}].episode must be null or "
                            f"an int >= 1, got {ep!r}")
            elif isinstance(eps, int) and not isinstance(eps, bool) \
                    and ep > eps:
                errs.append(f"actions[{i}].episode ({ep}) exceeds "
                            f"episodes ({eps})")
            t = a.get("t_s")
            if (not isinstance(t, numbers.Number)
                    or isinstance(t, bool) or not (t >= 0)):
                errs.append(f"actions[{i}].t_s must be a number >= 0, "
                            f"got {t!r}")
    mttr = need("mttr_s", dict)
    if isinstance(mttr, dict):
        c = mttr.get("count")
        if not isinstance(c, int) or isinstance(c, bool) or c < 0:
            errs.append(f"mttr_s.count must be an int >= 0, got {c!r}")
        for k in ("last", "mean"):
            v = mttr.get(k)
            if v is None:
                if isinstance(c, int) and not isinstance(c, bool) \
                        and c > 0:
                    errs.append(f"mttr_s.{k} is null with count {c}")
                continue
            if (not isinstance(v, numbers.Number)
                    or isinstance(v, bool) or v != v or not (v >= 0)):
                errs.append(f"mttr_s.{k} must be null or a finite "
                            f"number >= 0, got {v!r}")
            elif isinstance(c, int) and not isinstance(c, bool) \
                    and c == 0:
                errs.append(f"mttr_s.{k} is {v} with zero "
                            f"measurements")
    # role extras, validated whenever present
    if "world" in rec:
        v = rec["world"]
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errs.append(f"'world' must be an int >= 1 when present, "
                        f"got {v!r}")
    # schema-v7 preemption fields, validated whenever present (older
    # records simply predate them)
    if "cause" in rec and rec["cause"] is not None:
        if rec["cause"] not in RECOVERY_CAUSES:
            errs.append(f"'cause' must be null or one of "
                        f"{RECOVERY_CAUSES}, got {rec['cause']!r}")
    if "preempted" in rec and not isinstance(rec["preempted"], bool):
        errs.append(f"'preempted' must be a bool when present, got "
                    f"{rec['preempted']!r}")
    if "data_state" in rec and rec["data_state"] is not None:
        ds = rec["data_state"]
        if not isinstance(ds, dict):
            errs.append("'data_state' must be an object when present")
        else:
            for key in ("samples_consumed", "epoch", "cursor"):
                if key in ds:
                    v = ds[key]
                    if (not isinstance(v, int) or isinstance(v, bool)
                            or v < 0):
                        errs.append(f"data_state.{key} must be an int "
                                    f">= 0, got {v!r}")
            sid, ns = ds.get("shard_id"), ds.get("num_shards")
            for key, v in (("shard_id", sid), ("num_shards", ns)):
                if v is not None and (not isinstance(v, int)
                                      or isinstance(v, bool) or v < 0):
                    errs.append(f"data_state.{key} must be an int "
                                f">= 0, got {v!r}")
            if (isinstance(sid, int) and isinstance(ns, int)
                    and not isinstance(sid, bool)
                    and not isinstance(ns, bool) and ns >= 1
                    and not 0 <= sid < ns):
                errs.append(f"data_state.shard_id ({sid}) out of "
                            f"range for num_shards ({ns})")
    for opt in ("recoveries", "max_queue", "base_max_queue"):
        if opt in rec:
            v = rec[opt]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{opt!r} must be an int >= 0 when "
                            f"present, got {v!r}")
    if "duration_s" in rec:
        v = rec["duration_s"]
        if (not isinstance(v, numbers.Number) or isinstance(v, bool)
                or not (v >= 0)):
            errs.append(f"'duration_s' must be a number >= 0, got "
                        f"{v!r}")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


# -- trace record schema ----------------------------------------------------

def validate_trace_record(rec: Any) -> List[str]:
    """Schema check for one ``kind: trace`` JSONL record
    (``SpanRecorder.trace_record`` enriched by the exporter): the
    common envelope, a non-empty ``trace_id``, and a non-empty span
    list where every span belongs to the record's trace, carries a
    unique positive ``span_id``, and any ``parent_id`` references an
    EARLIER span id (span ids are allocated in causal order — a child
    pointing at a later or unknown parent means the recorder lost the
    chain, exactly the worker-thread interleaving bug this schema
    exists to catch)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types):
        return _need(rec, errs, key, types)

    _check_envelope(rec, errs)
    if rec.get("kind") != "trace":
        errs.append(f"kind must be 'trace', got {rec.get('kind')!r}")
    tid = need("trace_id", str)
    if isinstance(tid, str) and not tid:
        errs.append("trace_id must be non-empty")
    spans = need("spans", list)
    n = need("span_count", int)
    if isinstance(spans, list):
        if not spans:
            errs.append("spans must be non-empty (an empty trace is "
                        "not a trace)")
        if isinstance(n, int) and not isinstance(n, bool) \
                and n != len(spans):
            errs.append(f"span_count ({n}) != len(spans) "
                        f"({len(spans)})")
        all_ids = {sp.get("span_id") for sp in spans
                   if isinstance(sp, dict)}
        seen: set = set()
        for i, sp in enumerate(spans):
            if not isinstance(sp, dict):
                errs.append(f"spans[{i}] is not an object")
                continue
            name = sp.get("name")
            if not isinstance(name, str) or not name:
                errs.append(f"spans[{i}].name must be a non-empty "
                            f"string")
            if sp.get("ph") not in ("X", "i"):
                errs.append(f"spans[{i}].ph must be 'X' or 'i', got "
                            f"{sp.get('ph')!r}")
            if not isinstance(sp.get("ts"), numbers.Number):
                errs.append(f"spans[{i}].ts must be a number")
            if isinstance(tid, str) and sp.get("trace_id") != tid:
                errs.append(f"spans[{i}] belongs to trace "
                            f"{sp.get('trace_id')!r}, record is {tid!r}")
            sid = sp.get("span_id")
            if not isinstance(sid, int) or isinstance(sid, bool) \
                    or sid < 1:
                errs.append(f"spans[{i}].span_id must be an int >= 1")
                continue
            if sid in seen:
                errs.append(f"duplicate span_id {sid}")
            seen.add(sid)
            pid = sp.get("parent_id")
            if pid is not None:
                if not isinstance(pid, int) or isinstance(pid, bool):
                    errs.append(f"spans[{i}].parent_id must be an int")
                elif pid >= sid:
                    errs.append(
                        f"spans[{i}] (span_id {sid}) parents on "
                        f"{pid}, which is not causally earlier")
                elif pid not in all_ids:
                    # a parent that is not in the record at all means
                    # the chain's head was lost (e.g. evicted from a
                    # bounded recorder): not a complete trace
                    errs.append(
                        f"spans[{i}] (span_id {sid}) parents on "
                        f"{pid}, which is not in this record")
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


# -- profile record schema --------------------------------------------------

# observability.timeline.PROFILE_FIELDS (duplicated here so the
# stdlib-only CI loader never imports the timeline module; the pytest
# coverage pins the two tuples equal — the RUN_ANOMALY_KINDS
# discipline)
PROFILE_TIME_FIELDS = ("span_ms", "device_busy_ms", "compute_ms",
                       "collective_ms", "gap_ms", "overlap_ms")
_PROFILE_KERNEL_KINDS = ("compute", "collective")


def validate_profile_record(rec: Any) -> List[str]:
    """Schema check for one ``kind: profile`` JSONL record (the
    device-timeline attribution from ``observability.timeline`` via
    ``bench.py --profile`` or ``/profilez``, schema v8): the common
    envelope, a subject (``metric`` or ``entry_point``), the six
    non-negative timing fields, and the interval arithmetic a
    hand-built record gets wrong — busy never exceeds the span, gap
    reassembles span minus busy, the class unions bound the busy
    union from both sides, overlap fits inside BOTH classes, and the
    measured fraction is overlap over collective time.  ``top_kernels``
    entries must each name a known class; the optional KV fragmentation
    fields follow the bench-record rules (waste is a subset of the
    allocation, utilization is a fraction)."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]

    def need(key, types, allow_none=False):
        return _need(rec, errs, key, types, allow_none)

    _check_envelope(rec, errs)
    if rec.get("kind") != "profile":
        errs.append(f"kind must be 'profile', got {rec.get('kind')!r}")
    subject = rec.get("entry_point", rec.get("metric"))
    if not isinstance(subject, str) or not subject:
        errs.append("profile records must carry a non-empty "
                    "'entry_point' or 'metric'")
    vals = {}
    for key in PROFILE_TIME_FIELDS:
        v = need(key, numbers.Number)
        if isinstance(v, numbers.Number) and not isinstance(v, bool):
            if not (v >= 0):           # also rejects NaN
                errs.append(f"{key!r} must be >= 0, got {v!r}")
            else:
                vals[key] = float(v)
    frac = need("measured_overlap_fraction", numbers.Number)
    if (isinstance(frac, numbers.Number) and not isinstance(frac, bool)
            and not (0.0 <= frac <= 1.0)):
        errs.append(f"'measured_overlap_fraction' must be in [0, 1], "
                    f"got {frac!r}")

    def tol(x):
        # the producer rounds every field to 4 decimals independently;
        # merged-interval arithmetic is exact before rounding
        return max(0.01, 0.01 * x)

    if len(vals) == len(PROFILE_TIME_FIELDS):
        span, busy = vals["span_ms"], vals["device_busy_ms"]
        comp, coll = vals["compute_ms"], vals["collective_ms"]
        gap, ovl = vals["gap_ms"], vals["overlap_ms"]
        if busy > span + tol(span):
            errs.append(f"device_busy_ms ({busy}) exceeds span_ms "
                        f"({span})")
        if abs(gap - max(span - busy, 0.0)) > tol(span):
            errs.append(f"gap_ms ({gap}) != span_ms - device_busy_ms "
                        f"({span} - {busy})")
        if busy > comp + coll + tol(busy):
            errs.append(f"device_busy_ms ({busy}) exceeds compute_ms "
                        f"+ collective_ms ({comp} + {coll}) — the "
                        f"busy union is covered by the class unions")
        if busy + tol(busy) < max(comp, coll):
            errs.append(f"device_busy_ms ({busy}) below "
                        f"max(compute_ms, collective_ms) "
                        f"({comp}, {coll})")
        if ovl > min(comp, coll) + tol(ovl):
            errs.append(f"overlap_ms ({ovl}) exceeds a class union it "
                        f"is an intersection of ({comp}, {coll})")
        if (isinstance(frac, numbers.Number)
                and not isinstance(frac, bool)):
            if coll > 0:
                expect = min(max(ovl / coll, 0.0), 1.0)
                if abs(frac - expect) > max(0.01, 0.02 * expect):
                    errs.append(
                        f"measured_overlap_fraction ({frac}) "
                        f"inconsistent with overlap_ms/collective_ms "
                        f"({ovl}/{coll})")
            elif frac != 0.0:
                errs.append(f"measured_overlap_fraction ({frac}) with "
                            f"zero collective_ms")
    for opt in ("kernel_count", "lane_count"):
        if opt in rec:
            v = rec[opt]
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errs.append(f"{opt!r} must be an int >= 0 when "
                            f"present, got {v!r}")
    if "steps" in rec:
        v = rec["steps"]
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errs.append(f"'steps' must be an int >= 1 when present, "
                        f"got {v!r}")
    if "duration_ms" in rec:
        v = rec["duration_ms"]
        if (not isinstance(v, numbers.Number) or isinstance(v, bool)
                or not (v >= 0)):
            errs.append(f"'duration_ms' must be a number >= 0 when "
                        f"present, got {v!r}")
    if "trace_path" in rec and not isinstance(rec["trace_path"], str):
        errs.append("'trace_path' must be a string when present")
    if "top_kernels" in rec:
        top = rec["top_kernels"]
        if not isinstance(top, list):
            errs.append("'top_kernels' must be a list when present")
        else:
            for i, k in enumerate(top):
                if not isinstance(k, dict):
                    errs.append(f"top_kernels[{i}] is not an object")
                    continue
                name = k.get("name")
                if not isinstance(name, str) or not name:
                    errs.append(f"top_kernels[{i}].name must be a "
                                f"non-empty string")
                if k.get("kind") not in _PROFILE_KERNEL_KINDS:
                    errs.append(f"top_kernels[{i}].kind must be one "
                                f"of {_PROFILE_KERNEL_KINDS}, got "
                                f"{k.get('kind')!r}")
                c = k.get("count")
                if not isinstance(c, int) or isinstance(c, bool) \
                        or c < 1:
                    errs.append(f"top_kernels[{i}].count must be an "
                                f"int >= 1, got {c!r}")
                t = k.get("total_ms")
                if (not isinstance(t, numbers.Number)
                        or isinstance(t, bool) or not (t >= 0)):
                    errs.append(f"top_kernels[{i}].total_ms must be a "
                                f"number >= 0, got {t!r}")
    # KV fragmentation fields on serving profiles: the same shared
    # contract as the bench-record fields
    _check_kv_fields(rec, errs)
    try:
        json.dumps(rec)
    except (TypeError, ValueError) as e:
        errs.append(f"record is not JSON-serializable: {e}")
    return errs


def validate_telemetry_record(rec: Any) -> List[str]:
    """Dispatching validator: graph-lint, fleet and trace records (by
    ``kind``) go through their own schemas, everything else through
    the bench schema — so one stream may interleave bench
    measurements, lint findings (``bench.py --graph-lint``), fleet
    snapshots (``bench.py --fleet N``), request traces
    (``kind: trace``), cost-model dumps (``kind: memory``, from
    ``python -m apex_tpu.analysis --memory`` / ``bench.py``) and
    gradient-health dumps (``kind: numerics``, from
    ``bench.py --numerics`` / ``NumericsMonitor.to_record``) and
    training-run supervisor verdicts (``kind: run``, from
    ``bench.py --run`` / ``RunSupervisor.record``, schema v5) and
    recovery-controller snapshots (``kind: recovery``, from
    ``bench.py --chaos`` / ``RecoveryLog.record``, schema v6) and
    device-timeline attributions (``kind: profile``, from
    ``bench.py --profile`` / ``/profilez``, schema v8) and static
    replication ledgers (``kind: sharding``, from
    ``python -m apex_tpu.analysis --sharding`` / ``bench.py
    --graph-lint``, schema v13)."""
    if isinstance(rec, dict) and rec.get("kind") in (
            "graph_lint", "graph_lint_summary"):
        return validate_lint_record(rec)
    if isinstance(rec, dict) and rec.get("kind") == "fleet":
        return validate_fleet_record(rec)
    if isinstance(rec, dict) and rec.get("kind") == "trace":
        return validate_trace_record(rec)
    if isinstance(rec, dict) and rec.get("kind") == "memory":
        return validate_memory_record(rec)
    if isinstance(rec, dict) and rec.get("kind") == "numerics":
        return validate_numerics_record(rec)
    if isinstance(rec, dict) and rec.get("kind") == "run":
        return validate_run_record(rec)
    if isinstance(rec, dict) and rec.get("kind") == "recovery":
        return validate_recovery_record(rec)
    if isinstance(rec, dict) and rec.get("kind") == "profile":
        return validate_profile_record(rec)
    if isinstance(rec, dict) and rec.get("kind") == "sharding":
        return validate_sharding_record(rec)
    return validate_bench_record(rec)


def validate_telemetry_jsonl(lines: Iterable[str]) -> List[str]:
    """Validate a mixed bench + graph-lint + fleet + trace + memory +
    numerics + run JSONL stream."""
    return _validate_jsonl(lines, validate_telemetry_record)


def _validate_jsonl(lines: Iterable[str], validate) -> List[str]:
    errs: List[str] = []
    n = 0
    for i, raw in enumerate(lines, 1):
        raw = raw.strip()
        if not raw:
            continue
        n += 1
        try:
            rec = json.loads(raw)
        except ValueError as e:
            errs.append(f"line {i}: not JSON ({e})")
            continue
        label = rec.get("metric") or rec.get("kind") or "?" \
            if isinstance(rec, dict) else "?"
        for e in validate(rec):
            errs.append(f"line {i} ({label}): {e}")
    if n == 0:
        errs.append("no records found")
    return errs
