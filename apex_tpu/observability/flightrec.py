"""Flight recorder: a bounded ring of operational events for
post-mortems.

Metrics tell you *how much*, spans tell you *how long*; the flight
ring tells you *what happened right before it broke*.  It records the
rare, state-changing transitions a 3am page needs — circuit-breaker
opens/closes, failovers, drains, stall-watchdog fires, amp-scaler
skips, injected faults — in a fixed-capacity deque, so a process that
runs for weeks holds exactly the last ``capacity`` transitions and
nothing more.  ``dump()`` writes the ring as JSONL the moment a fault
fires (``Fleet(flight_dump_path=...)`` wires that automatically).

Every event carries a monotonically-increasing ``seq`` (survives ring
wraparound — the gap between the first retained ``seq`` and 0 is the
drop count), the recorder-relative timestamp, the event ``kind``, and
arbitrary attrs.  Appends are lock-protected and cheap (one dict + one
deque append), safe from the fleet's worker threads.

Producers in-tree: ``fleet.Fleet`` (failover / shed / retry / deadline
/ stall-watchdog / drain), ``fleet.health.ReplicaHealth`` (breaker
transitions), ``fleet.faults.FaultyReplica`` (injected faults),
``amp.record_scaler`` (scaler skips).  Fleet events for tagged
requests carry the request's ``tenant`` (shed and deadline events say
WHOSE request suffered); aggregate transitions touching several
requests (failover reclaim, deadline sweep) carry the affected
``tenants`` list — ``snapshot(tenant=...)`` / ``/flightz?tenant=``
filter on both.  All default to the process ring
(:func:`get_ring`) so one dump shows the interleaved story; pass an
explicit ring to isolate a fleet (tests do).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["EventRing", "event_matches_tenant", "get_ring",
           "set_ring", "resolve", "record"]


def event_matches_tenant(event: Dict[str, Any], tenant: str) -> bool:
    """THE membership rule for "is this event part of ``tenant``'s
    story": a per-request event stamped ``tenant: <name>`` matches,
    and so does an aggregate transition (failover reclaim, deadline
    sweep, preemption) listing the name in its ``tenants`` list.
    Both :meth:`EventRing.snapshot` and ``/flightz?tenant=`` call
    this one function, so a post-mortem dump filter and a live scrape
    can never drift apart."""
    return (event.get("tenant") == tenant
            or tenant in (event.get("tenants") or ()))


class EventRing:
    """Bounded, thread-safe operational-event ring."""

    def __init__(self, capacity: int = 1024, clock=time.perf_counter):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._seq = 0

    def append(self, kind: str, **attrs) -> Dict[str, Any]:
        """Record one transition; returns the stored event."""
        ev = {"kind": kind}
        if attrs:
            ev.update(attrs)
        with self._lock:
            # clock read under the lock WITH the seq assignment, so
            # timestamp order and seq order can never disagree in a
            # dump (time running backwards across adjacent seqs would
            # reorder causally-ordered transitions for a reader
            # sorting by t)
            ev["t"] = self._clock() - self._t0
            ev["seq"] = self._seq
            self._seq += 1
            self._events.append(ev)
        return ev

    def snapshot(self, kind: Optional[str] = None,
                 tenant: Optional[str] = None) -> List[Dict[str, Any]]:
        """Retained events oldest-first (optionally one kind and/or
        one tenant's story).  The tenant filter matches both the
        per-request events stamped ``tenant: <name>`` and the
        aggregate transitions (failover reclaim, deadline sweep) that
        list every affected tenant in ``tenants`` — the same rule
        ``/flightz?tenant=`` serves, so a post-mortem and a live
        scrape answer "whose request suffered" identically."""
        with self._lock:
            evs = [dict(e) for e in self._events]
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        if tenant is not None:
            evs = [e for e in evs if event_matches_tenant(e, tenant)]
        return evs

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    @property
    def total(self) -> int:
        """Events ever appended (>= len: the ring drops oldest)."""
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._seq - len(self._events)

    def stats(self) -> Dict[str, int]:
        """One consistent accounting snapshot (capacity / total ever
        appended / retained / dropped) — the ``/flightz`` header; taken
        under one lock acquisition so ``total == retained + dropped``
        holds even mid-append."""
        with self._lock:
            n = len(self._events)
            return {"capacity": self.capacity, "total": self._seq,
                    "retained": n, "dropped": self._seq - n}

    def clear(self):
        with self._lock:
            self._events.clear()

    def dump(self, path: str) -> str:
        """Write the ring as JSONL (atomic replace): one header line
        with the drop accounting, then every retained event oldest
        first — the post-mortem artifact."""
        with self._lock:
            evs = [dict(e) for e in self._events]
            header = {"kind": "flight_ring", "capacity": self.capacity,
                      "total": self._seq,
                      "dropped": self._seq - len(evs)}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            # default=repr: a producer may have appended a non-JSON
            # attr (np scalar, exception object); the post-mortem dump
            # must stringify it, never raise mid-failover
            f.write(json.dumps(header) + "\n")
            for ev in evs:
                f.write(json.dumps(ev, default=repr) + "\n")
        os.replace(tmp, path)
        return path


_global_ring = EventRing()


def get_ring() -> EventRing:
    """The process-wide default ring (fleet health, fault harness, and
    amp scaler skips land here unless handed an explicit ring)."""
    return _global_ring


def set_ring(ring: EventRing) -> EventRing:
    global _global_ring
    prev, _global_ring = _global_ring, ring
    return prev


def resolve(ring: Optional[EventRing]) -> EventRing:
    """An explicit ring, else the CURRENT process ring.  Producers
    holding an optional ring call this per append (not once at
    construction) so a :func:`set_ring` swap moves every producer's
    story to the new ring together."""
    return ring if ring is not None else _global_ring


def record(kind: str, **attrs) -> Dict[str, Any]:
    """Append to the process-wide default ring."""
    return _global_ring.append(kind, **attrs)
