"""Step-time attribution: where a distributed train step's wall time
goes — compute vs gradient communication, per fabric level.

``bench.py --comm`` (PR 5) reports on-wire *bytes* per level; ROADMAP
item 2 (overlap gradient comm with backward compute) gates on
*step-time* improving, which needs the decomposition this module
measures.  The method is **blocked-fetch differential timing**, run
entirely OFF the jitted hot path:

- three separately-jitted programs are timed with a hard
  device-to-host fetch as the completion barrier (the same discipline
  ``bench.timed`` uses — ``block_until_ready`` can return early on
  tunneled device platforms, a D2H fetch cannot): the **full step**
  (compute + collectives), its **compute twin** (identical step with
  the gradient allreduce elided — ``DistributedDataParallel.
  comm_enabled = False`` builds it from the same step function), and
  the **isolated comm program** (just the allreduce on grads-shaped
  buffers);
- nothing is inserted into any jitted graph — no callbacks, no
  timers, no extra host transfers — so the pinned zero-host-transfer
  audit (tests/test_step_graph_audit.py) holds with attribution
  enabled by construction.

The decomposition::

    comm_ms    = max(step_ms - compute_ms, 0)      # comm on the critical path
    overlap    = 1 - comm_ms / comm_isolated_ms    # clamped to [0, 1]

``overlap_fraction`` is the share of the isolated comm time the
compiler hid under compute.  With today's reduce-everything-after-
backward schedule it measures ~0.0 — the baseline the overlap work
must beat.  ``compute_ms + comm_ms == step_ms`` by construction (up to
the clamp), which is the wall-clock consistency
``exporters.validate_bench_record`` pins on attribution records.

Differencing is an *inference*; the device timeline is a
*measurement*.  ``attribute_step(..., capture_timeline=True)`` runs
one extra pass of the full step under a fresh profiler window, parses
the Chrome trace with ``observability.timeline``, and attaches the
measured split — per-kernel device busy time, the compute vs
collective unions, and a ``measured_overlap_fraction`` from actual
kernel-interval overlap — plus a :func:`timeline_consistency` verdict
pinning the differenced comm share against the measured one within a
stated tolerance.  When the two disagree beyond it, trust the
timeline: differencing assumes the compute twin and the full step
schedule identically, which the compiler does not promise.

Per-level attribution takes the ICI/DCN labels from
``parallel.allreduce_comm_plan``: the measured comm time is split
across buckets by wire bytes and within a bucket by its
``ici_wire_bytes`` / ``dcn_wire_bytes`` (a flat bucket is one fabric —
its time reports under ``ici``; the hierarchical topology is what
makes the ``dcn`` column meaningful).  Pass ``ici_step=`` (a jitted
program running only the in-slice collectives) to replace the
byte-proportional level split with a measured one.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["blocked_time", "attribute_step", "timeline_consistency",
           "ATTRIBUTION_FIELDS", "OVERLAP_SCHEDULE_FIELDS"]

# the fields every step-attribution bench record must carry
# (exporters.validate_bench_record keys its checks off
# ``overlap_fraction``)
ATTRIBUTION_FIELDS = ("step_ms", "compute_ms", "comm_ms",
                      "comm_isolated_ms", "overlap_fraction",
                      "ici_ms", "dcn_ms")

# the schedule an attribution record measured (PR 14): which
# bucket-issue schedule the timed step ran — "overlapped" (per-stage
# reductions interleaved with backward) or "reduce_after_backward"
# (the classic baseline) — plus the stage count and stage-level issue
# order.  Duplicated stdlib-side as exporters.OVERLAP_SCHEDULE_FIELDS
# (pinned equal in tests); at schema v9 every fresh
# train_step_attribution_* record carries them, so a dashboard can
# split the overlap trend by schedule instead of guessing from metric
# names.
OVERLAP_SCHEDULE_FIELDS = ("overlap_mode", "n_stages", "issue_order")


def _block(out) -> None:
    """Hard completion barrier: one D2H fetch of an output leaf.  A
    fetch cannot complete before the dispatched program finishes; see
    the module docstring for why ``block_until_ready`` is not used."""
    import jax
    import jax.numpy as jnp
    leaves = jax.tree_util.tree_leaves(out)
    if leaves:
        float(jnp.sum(leaves[0]).astype(jnp.float32))


def blocked_time(fn: Callable, *args, iters: int = 10,
                 warmup: int = 2) -> float:
    """Mean seconds per call of ``fn(*args)`` over ``iters`` timed
    calls after ``warmup`` untimed ones (compile + cache warm), with
    the blocked-fetch barrier before starting and after the last
    call."""
    if iters < 1 or warmup < 0:
        raise ValueError(f"need iters >= 1 and warmup >= 0, got "
                         f"iters={iters}, warmup={warmup}")
    out = None
    for _ in range(warmup):
        out = fn(*args)
    # barrier BEFORE t0 either way: with warmup=0 there is no output
    # to fetch yet, so drain in-flight transfers of the inputs instead
    # — otherwise previously dispatched async work lands inside the
    # timed window
    _block(out if warmup else args)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _block(out)
    return (time.perf_counter() - t0) / iters


def _bucket_level_bytes(bucket: Dict[str, Any]):
    """(ici_bytes, dcn_bytes) attribution weights for one comm-plan
    bucket.  Hierarchical buckets split by the plan's per-level wire
    bytes (which sum to the bucket's total); a flat bucket is a single
    fabric, so its whole payload weighs on the ``ici`` column."""
    if bucket.get("topology") == "hierarchical":
        return (float(bucket["ici_wire_bytes"]),
                float(bucket["dcn_wire_bytes"]))
    b = float(bucket.get("wire_bytes", bucket.get("bytes", 0)))
    return b, 0.0


def timeline_consistency(attribution: Dict[str, Any],
                         tl: Dict[str, Any],
                         tol: float = 0.35) -> Dict[str, Any]:
    """Pin the differencing estimate against the measured split.

    Compares the comm share of a step the two ways: differenced —
    critical-path ``comm_ms / step_ms`` (host wall clock) — vs
    measured — the collective time NOT hidden under compute over the
    capture span (``(collective_ms - overlap_ms) / span_ms``, device
    timeline).  ``tol`` is an ABSOLUTE tolerance on the fraction
    difference: both methods see the same schedule, but differencing
    folds dispatch gaps and compiler-schedule drift between the twin
    programs into its estimate, so the stated tolerance is loose by
    design — the check catches the methodology being *wrong* (a twin
    that elides more than the collectives), not timer noise."""
    step_ms = float(attribution.get("step_ms", 0.0) or 0.0)
    diff_frac = (float(attribution.get("comm_ms", 0.0)) / step_ms
                 if step_ms > 0 else 0.0)
    span_ms = float(tl.get("span_ms", 0.0) or 0.0)
    vis = max(float(tl.get("collective_ms", 0.0))
              - float(tl.get("overlap_ms", 0.0)), 0.0)
    meas_frac = (vis / span_ms) if span_ms > 0 else 0.0
    delta = abs(diff_frac - meas_frac)
    return {"differenced_comm_fraction": round(diff_frac, 4),
            "measured_comm_fraction": round(meas_frac, 4),
            "abs_diff": round(delta, 4),
            "tol": float(tol),
            "consistent": bool(delta <= tol)}


def attribute_step(full_step: Callable, compute_step: Callable,
                   comm_step: Callable, args: Sequence[Any] = (),
                   plan: Optional[List[dict]] = None,
                   iters: int = 10, warmup: int = 2,
                   ici_step: Optional[Callable] = None,
                   schedule: Optional[Dict[str, Any]] = None,
                   capture_timeline: bool = False,
                   capture_dir: Optional[str] = None,
                   capture_iters: Optional[int] = None,
                   timeline_modules: Optional[Sequence[str]] = None,
                   consistency_tol: float = 0.35
                   ) -> Dict[str, Any]:
    """Measure and decompose one train step (see module docstring).

    ``full_step`` / ``compute_step`` / ``comm_step`` (and the optional
    ``ici_step``) are called as ``fn(*args)``; each should be its own
    jitted program over the SAME shapes.  ``plan`` is the
    ``parallel.allreduce_comm_plan`` of the step's gradient reduction
    (or the ``buckets`` of an ``overlap_comm_schedule``, whose
    ``stage``/``issue_order`` labels ride into the output buckets);
    without one the comm time reports as a single unlabeled bucket on
    the ``ici`` column.

    ``schedule`` is the step's ``parallel.overlap_comm_schedule`` (or
    ``DistributedDataParallel.last_overlap_schedule``): its
    ``OVERLAP_SCHEDULE_FIELDS`` are folded onto the attribution dict
    so the emitted record says WHICH bucket-issue schedule it
    measured.  ``None`` stamps the classic single-stage
    reduce-after-backward shape — every attribution record carries
    the fields either way (schema v9).

    ``capture_timeline=True`` additionally runs ``capture_iters``
    (default ``iters``) warm passes of the FULL step under a fresh
    profiler window — after the timed loops, so the capture never
    contaminates the differencing measurements — and attaches the
    parsed device-timeline attribution under ``timeline`` (per-step,
    ``observability.timeline.analyze_capture``), the headline
    ``measured_overlap_fraction``, and the
    :func:`timeline_consistency` verdict under ``consistency``.
    ``timeline_modules`` restricts parsing to the step's own HLO
    module(s) (e.g. ``("jit_step",)``) so the blocked-fetch plumbing
    does not attribute as step time.

    Returns the attribution dict (all times in ms)::

        {step_ms, compute_ms, comm_ms, comm_isolated_ms,
         overlap_fraction, ici_ms, dcn_ms, buckets: [...],
         timeline?: {...}, measured_overlap_fraction?,
         consistency?: {...}}
    """
    step_ms = blocked_time(full_step, *args, iters=iters,
                           warmup=warmup) * 1e3
    compute_ms = blocked_time(compute_step, *args, iters=iters,
                              warmup=warmup) * 1e3
    comm_isolated_ms = blocked_time(comm_step, *args, iters=iters,
                                    warmup=warmup) * 1e3
    # the decomposition model says compute <= step (the twin is the
    # step minus its collectives); a twin that times SLOWER than the
    # full step — routine on the oversubscribed CPU smoke mesh, where
    # the collectives' rendezvous accidentally staggers the device
    # threads — would otherwise publish a record violating its own
    # compute+comm==step identity.  Clamp to the model and surface the
    # excess as ``compute_twin_excess_ms`` so the record stays
    # schema-consistent while the anomaly stays visible.
    twin_excess = max(compute_ms - step_ms, 0.0)
    compute_ms = min(compute_ms, step_ms)
    comm_ms = max(step_ms - compute_ms, 0.0)
    if comm_isolated_ms > 0.0:
        overlap = 1.0 - comm_ms / comm_isolated_ms
    else:
        overlap = 0.0
    overlap = min(max(overlap, 0.0), 1.0)

    # per-level split of the measured comm time, labeled from the plan
    buckets = list(plan) if plan else [{"topology": "flat",
                                        "wire_bytes": 1}]
    weights = [_bucket_level_bytes(b) for b in buckets]
    total_w = sum(i + d for i, d in weights)
    if total_w <= 0.0:
        # a plan whose buckets carry no recognized byte weight cannot
        # label the split — fall back to the single-fabric default
        # (everything on the first bucket's ici column) so ici+dcn
        # still reassembles comm_isolated_ms and the record passes its
        # own schema
        weights = [(1.0, 0.0)] + [(0.0, 0.0)] * (len(weights) - 1)
        total_w = 1.0
    if ici_step is not None:
        ici_total = min(blocked_time(ici_step, *args, iters=iters,
                                     warmup=warmup) * 1e3,
                        comm_isolated_ms)
        dcn_total = comm_isolated_ms - ici_total
        iw = sum(i for i, _ in weights)
        dw = sum(d for _, d in weights)
        # a level with zero byte weight cannot absorb measured time —
        # fold the residue into the other level instead of dropping it
        # (a single-fabric plan with a measured ici_step residual
        # would otherwise emit ici+dcn < comm_isolated and fail the
        # schema's reassembly check)
        if dw == 0.0:
            ici_total, dcn_total = comm_isolated_ms, 0.0
        elif iw == 0.0:
            ici_total, dcn_total = 0.0, comm_isolated_ms
        # distribute each measured level over buckets by that level's
        # bytes
        split = [(ici_total * i / (iw or 1.0),
                  dcn_total * d / (dw or 1.0)) for i, d in weights]
    else:
        split = [(comm_isolated_ms * i / total_w,
                  comm_isolated_ms * d / total_w) for i, d in weights]

    out_buckets = []
    for b, (ici_ms, dcn_ms) in zip(buckets, split):
        rec = {"ici_ms": round(ici_ms, 4), "dcn_ms": round(dcn_ms, 4)}
        for k in ("comm_dtype", "elements", "topology", "cause",
                  "ici_wire_bytes", "dcn_wire_bytes", "wire_bytes",
                  "stage", "issue_order"):
            if k in b:
                rec[k] = b[k]
        out_buckets.append(rec)

    # which bucket-issue schedule the timed step ran — lazily through
    # parallel (the owner of the schedule shape) so this module stays
    # jax-free at import
    from ..parallel import distributed as _dist
    out = {"step_ms": round(step_ms, 4),
           "compute_ms": round(compute_ms, 4),
           "comm_ms": round(comm_ms, 4),
           "comm_isolated_ms": round(comm_isolated_ms, 4),
           "overlap_fraction": round(overlap, 4),
           "ici_ms": round(sum(i for i, _ in split), 4),
           "dcn_ms": round(sum(d for _, d in split), 4),
           **_dist.overlap_schedule_fields(schedule),
           "buckets": out_buckets}
    if twin_excess > 0.0:
        out["compute_twin_excess_ms"] = round(twin_excess, 4)

    if capture_timeline:
        from . import timeline as tlmod
        n = capture_iters if capture_iters is not None else iters
        tl = tlmod.capture(full_step, *args, iters=max(n, 1),
                           logdir=capture_dir,
                           modules=timeline_modules)
        out["timeline"] = tl
        out["measured_overlap_fraction"] = \
            tl["measured_overlap_fraction"]
        out["consistency"] = timeline_consistency(
            out, tl, tol=consistency_tol)
    return out
