"""Span/event recorder: wall-clock ranges → Chrome trace / JSONL.

Layered on ``apex_tpu.utils.profiler``: every :meth:`SpanRecorder.span`
also opens the profiler's nvtx-parity range (``jax.named_scope`` +
``jax.profiler.TraceAnnotation``), so a span shows up in xprof captures
*and* in this recorder's exportable timeline.  The recorder itself is
pure host-side bookkeeping — opening a span inside a jitted trace names
the traced HLO but times only the (one-off) trace, so put spans around
eager sections: admission, harvest, checkpointing, data loading.

Exports:

- **Chrome trace JSON** (``chrome://tracing`` / Perfetto): complete
  events (``ph: "X"``, microsecond timestamps) plus instant events.
- **JSONL event log**: one JSON object per event, machine-readable for
  downstream analysis (the bench/CI side of the telemetry trail).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["SpanRecorder", "get_recorder", "set_recorder", "span",
           "event", "export_chrome_trace", "export_jsonl"]


class SpanRecorder:
    """Thread-safe span/event buffer with a per-recorder time origin."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._t0 = clock()
        self._pid = os.getpid()

    def _now_us(self) -> float:
        return (self._clock() - self._t0) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Record a complete event for the enclosed block; also opens
        the profiler range so xprof attribution matches this timeline.
        Exception-safe and nestable (nesting renders as stacked slices
        in the Chrome trace viewer)."""
        from ..utils import profiler
        tid = threading.get_ident()
        begin = self._now_us()
        with profiler.nvtx_range(name):
            try:
                yield self
            finally:
                end = self._now_us()
                ev = {"name": name, "ph": "X", "ts": begin,
                      "dur": max(end - begin, 0.0),
                      "pid": self._pid, "tid": tid}
                if attrs:
                    ev["args"] = dict(attrs)
                with self._lock:
                    self._events.append(ev)

    def event(self, name: str, **attrs):
        """Instant (zero-duration) event — loss-scale changes, engine
        admissions, flush points."""
        ev = {"name": name, "ph": "i", "s": "t", "ts": self._now_us(),
              "pid": self._pid, "tid": threading.get_ident()}
        if attrs:
            ev["args"] = dict(attrs)
        with self._lock:
            self._events.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def clear(self):
        with self._lock:
            self._events.clear()

    # -- exports -----------------------------------------------------------
    def chrome_trace(self) -> Dict[str, Any]:
        """The Chrome trace-event JSON object (traceEvents array form)."""
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)
        return path

    def export_jsonl(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for ev in self.events():
                f.write(json.dumps(ev) + "\n")
        os.replace(tmp, path)
        return path


_global_recorder = SpanRecorder()


def get_recorder() -> SpanRecorder:
    return _global_recorder


def set_recorder(recorder: SpanRecorder) -> SpanRecorder:
    global _global_recorder
    prev, _global_recorder = _global_recorder, recorder
    return prev


def span(name: str, **attrs):
    """``with observability.span("checkpoint_save"): ...`` on the
    process-wide default recorder."""
    return _global_recorder.span(name, **attrs)


def event(name: str, **attrs):
    return _global_recorder.event(name, **attrs)


def export_chrome_trace(path: str,
                        recorder: Optional[SpanRecorder] = None) -> str:
    return (recorder or _global_recorder).export_chrome_trace(path)


def export_jsonl(path: str,
                 recorder: Optional[SpanRecorder] = None) -> str:
    return (recorder or _global_recorder).export_jsonl(path)
