"""Span/event recorder: wall-clock ranges → Chrome trace / JSONL,
plus the request-scoped distributed-trace context the fleet layer
propagates (the flight recorder's causal spine).

Layered on ``apex_tpu.utils.profiler``: every :meth:`SpanRecorder.span`
also opens the profiler's nvtx-parity range (``jax.named_scope`` +
``jax.profiler.TraceAnnotation``), so a span shows up in xprof captures
*and* in this recorder's exportable timeline.  The recorder itself is
pure host-side bookkeeping — opening a span inside a jitted trace names
the traced HLO but times only the (one-off) trace, so put spans around
eager sections: admission, harvest, checkpointing, data loading.

**Trace context.**  Every span/event carries a recorder-allocated
``span_id`` (monotonic under the recorder lock, so allocation order IS
causal order: a child's id is always greater than its parent's).  A
*trace* groups spans end-to-end across components and threads:

- :func:`new_trace_id` mints a process-unique trace id (``Fleet.submit``
  mints one per request);
- the *ambient* context is a :class:`contextvars.ContextVar`, so it is
  **per-thread-of-execution**: a span opened on one thread can never
  adopt a parent another thread happens to have open (the PR 1 recorder
  had no parentage at all — worker-thread spans interleaved freely);
- :meth:`SpanRecorder.span` reads the ambient context for its trace and
  parent unless given explicit ``trace_id=`` / ``parent_id=``, and
  installs itself as the ambient parent for the enclosed block;
- :meth:`SpanRecorder.activate` installs a (trace_id, span_id) pair as
  the ambient context *without* recording anything — how the fleet
  hands a worker thread the dispatch span to parent engine-internal
  spans under (``ThreadPoolExecutor`` workers start with an empty
  context and are reused, so the context must be scoped; the token
  reset in ``finally`` guarantees no leakage between pool tasks).

**Tenant attribution.**  Attrs ride into each event's ``args``
verbatim, and the fleet uses exactly that: a tagged request's
``tenant`` / ``priority`` are stamped on EVERY span and event of its
trace (submit, route, dispatch, engine queue/prefill, finish — and
the failure hops: fault, reclaim, re-dispatch after failover), so
filtering a Chrome trace or a ``trace_record`` by ``args.tenant``
yields one tenant's complete story with no joins.  The recorder adds
no tenant-specific machinery — the contract is the *stamping
discipline* in ``fleet.Fleet._trace_ev``, pinned by tests.

Exports:

- **Chrome trace JSON** (``chrome://tracing`` / Perfetto): complete
  events (``ph: "X"``, microsecond timestamps) plus instant events.
- **JSONL event log**: one JSON object per event, machine-readable for
  downstream analysis (the bench/CI side of the telemetry trail).
- **Trace records** (:meth:`SpanRecorder.trace_record`): one
  schema-versioned ``kind: trace`` object per trace id, validated by
  ``exporters.validate_trace_record`` — the per-request flight record.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["SpanRecorder", "get_recorder", "set_recorder", "span",
           "event", "export_chrome_trace", "export_jsonl",
           "new_trace_id", "current_trace", "maybe_span", "maybe_event",
           "DEFAULT_MAX_EVENTS"]

# ambient (recorder, trace_id, span_id) of the innermost open span/
# activation on THIS thread of execution; contextvars give each thread
# its own slot.  The owning RECORDER rides along because span ids are
# per-recorder: an ambient parent minted by one recorder must never be
# adopted into another recorder's id space (dangling/colliding
# parent_ids) — maybe_span/maybe_event record into the ambient
# recorder, and _resolve only adopts a context it owns.
_CURRENT: contextvars.ContextVar[
    Optional[Tuple["SpanRecorder", str, Optional[int]]]] = \
    contextvars.ContextVar("apex_tpu_trace", default=None)

_trace_lock = threading.Lock()
_trace_counter = 0


def new_trace_id(prefix: str = "t") -> str:
    """Process-unique trace id (``t-<pid>-<n>``): cheap, ordered, and
    readable in artifacts — no uuid dependency, and the counter makes
    ids deterministic per process for test pinning."""
    global _trace_counter
    with _trace_lock:
        _trace_counter += 1
        n = _trace_counter
    return f"{prefix}-{os.getpid():x}-{n:x}"


def current_trace() -> Optional[Tuple[str, Optional[int]]]:
    """The ambient ``(trace_id, span_id)`` of this thread, or None —
    the gate :func:`maybe_span` uses so untraced hot paths record
    nothing."""
    cur = _CURRENT.get()
    return None if cur is None else (cur[1], cur[2])


class SpanRecorder:
    """Thread-safe span/event buffer with a per-recorder time origin.

    ``max_events`` bounds the buffer (oldest events drop first) — the
    flight-recorder discipline for long-running processes; ``None``
    keeps the PR 1 unbounded behavior for short captures."""

    def __init__(self, clock=time.perf_counter,
                 max_events: Optional[int] = None):
        self._clock = clock
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max_events)
        self._t0 = clock()
        self._pid = os.getpid()
        self._next_span = 0

    def _now_us(self) -> float:
        return (self._clock() - self._t0) * 1e6

    def _alloc_span(self) -> int:
        """Next span id, allocated under the lock at span ENTRY, so ids
        are causally ordered: a child (entered after its parent) always
        carries a larger id than the parent."""
        with self._lock:
            self._next_span += 1
            return self._next_span

    def _resolve(self, trace_id, parent_id):
        """Fill trace/parent from the ambient context when not given
        explicitly.  An explicit ``trace_id`` with no ``parent_id``
        stays parentless (a new root) — it must NOT adopt whatever
        span another trace has open on this thread.  A context owned
        by a DIFFERENT recorder is never adopted either: its span ids
        live in that recorder's id space."""
        if trace_id is None:
            cur = _CURRENT.get()
            if cur is not None and cur[0] is self:
                trace_id = cur[1]
                if parent_id is None:
                    parent_id = cur[2]
        return trace_id, parent_id

    def _stamp(self, ev, trace_id, span_id, parent_id):
        ev["span_id"] = span_id
        if trace_id is not None:
            ev["trace_id"] = trace_id
        if parent_id is not None:
            ev["parent_id"] = parent_id
        return ev

    @contextlib.contextmanager
    def span(self, name: str, trace_id: Optional[str] = None,
             parent_id: Optional[int] = None, **attrs):
        """Record a complete event for the enclosed block; also opens
        the profiler range so xprof attribution matches this timeline.
        Exception-safe and nestable (nesting renders as stacked slices
        in the Chrome trace viewer).  While the block runs, this span
        is the ambient parent for spans/events opened on the SAME
        thread of execution; the context token is reset in ``finally``
        so reused pool threads never inherit a stale parent."""
        from ..utils import profiler
        tid = threading.get_ident()
        trace_id, parent_id = self._resolve(trace_id, parent_id)
        span_id = self._alloc_span()
        token = _CURRENT.set((self, trace_id, span_id)) \
            if trace_id is not None else None
        begin = self._now_us()
        # the token reset must be unconditional: if even the profiler
        # range fails to OPEN, a reused pool thread must not keep this
        # span as its ambient parent
        try:
            with profiler.nvtx_range(name):
                yield self
        finally:
            if token is not None:
                _CURRENT.reset(token)
            end = self._now_us()
            ev = {"name": name, "ph": "X", "ts": begin,
                  "dur": max(end - begin, 0.0),
                  "pid": self._pid, "tid": tid}
            self._stamp(ev, trace_id, span_id, parent_id)
            if attrs:
                ev["args"] = dict(attrs)
            with self._lock:
                self._events.append(ev)

    def event(self, name: str, trace_id: Optional[str] = None,
              parent_id: Optional[int] = None, **attrs) -> int:
        """Instant (zero-duration) event — loss-scale changes, engine
        admissions, flush points, request-lifecycle transitions.
        Returns the event's span id so callers chaining a causal
        lifecycle (submit → route → dispatch → …) can parent the next
        hop on this one."""
        trace_id, parent_id = self._resolve(trace_id, parent_id)
        span_id = self._alloc_span()
        ev = {"name": name, "ph": "i", "s": "t", "ts": self._now_us(),
              "pid": self._pid, "tid": threading.get_ident()}
        self._stamp(ev, trace_id, span_id, parent_id)
        if attrs:
            ev["args"] = dict(attrs)
        with self._lock:
            self._events.append(ev)
        return span_id

    @contextlib.contextmanager
    def activate(self, trace_id: str, span_id: Optional[int] = None):
        """Install ``(trace_id, span_id)`` as this thread's ambient
        context WITHOUT recording anything.  The cross-thread handoff:
        the fleet step pool activates the request/replica context in
        the worker so engine-internal spans parent correctly."""
        token = _CURRENT.set((self, trace_id, span_id))
        try:
            yield self
        finally:
            _CURRENT.reset(token)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._events]

    def clear(self):
        with self._lock:
            self._events.clear()

    # -- trace queries -----------------------------------------------------
    def trace_ids(self) -> List[str]:
        """Distinct trace ids with at least one retained event, in
        first-seen order — the ``/tracez`` index (a bounded recorder
        lists only traces whose events survived eviction)."""
        seen: Dict[str, None] = {}
        for e in self.events():
            tid = e.get("trace_id")
            if tid is not None and tid not in seen:
                seen[tid] = None
        return list(seen)

    def trace(self, trace_id: str) -> List[Dict[str, Any]]:
        """All events of one trace, in span-id (causal allocation)
        order — begin-time order would interleave a parent span (whose
        complete event is appended at EXIT) after its children."""
        evs = [e for e in self.events() if e.get("trace_id") == trace_id]
        evs.sort(key=lambda e: e["span_id"])
        return evs

    def trace_record(self, trace_id: str) -> Dict[str, Any]:
        """The ``kind: trace`` JSONL record for one trace (feed it
        through ``JsonlExporter``/``enrich`` for the envelope;
        ``exporters.validate_trace_record`` pins the shape)."""
        spans = self.trace(trace_id)
        return {"kind": "trace", "trace_id": trace_id,
                "spans": spans, "span_count": len(spans)}

    # -- exports -----------------------------------------------------------
    def chrome_trace(self) -> Dict[str, Any]:
        """The Chrome trace-event JSON object (traceEvents array form)."""
        return {"traceEvents": self.events(), "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.chrome_trace(), f)
        os.replace(tmp, path)
        return path

    def export_jsonl(self, path: str) -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for ev in self.events():
                f.write(json.dumps(ev) + "\n")
        os.replace(tmp, path)
        return path


# the process default is BOUNDED (flight-recorder discipline): a fleet
# traces every request by default, and a process that serves for weeks
# must hold the last DEFAULT_MAX_EVENTS spans — not all of them.  Old
# traces evict oldest-first; a trace whose head was evicted no longer
# validates as a complete ``kind: trace`` record (the validator flags
# the missing parent), which is the honest answer.  Install
# ``set_recorder(SpanRecorder())`` for an unbounded short capture.
DEFAULT_MAX_EVENTS = 65536

_global_recorder = SpanRecorder(max_events=DEFAULT_MAX_EVENTS)


def get_recorder() -> SpanRecorder:
    return _global_recorder


def set_recorder(recorder: SpanRecorder) -> SpanRecorder:
    global _global_recorder
    prev, _global_recorder = _global_recorder, recorder
    return prev


def span(name: str, **attrs):
    """``with observability.span("checkpoint_save"): ...`` on the
    process-wide default recorder."""
    return _global_recorder.span(name, **attrs)


def event(name: str, **attrs):
    return _global_recorder.event(name, **attrs)


@contextlib.contextmanager
def maybe_span(name: str, **attrs):
    """Span ONLY when a trace context is ambient on this thread;
    otherwise a no-op.  Records into the recorder that OWNS the
    ambient context (its parent span ids live in that recorder's id
    space), which is the default recorder on the normal fleet path.
    The engine hot paths (queue/prefill/window-decode) use this so a
    standalone engine with no fleet trace records nothing per step —
    tracing costs are opt-in per request, and an untraced process's
    recorder never grows."""
    cur = _CURRENT.get()
    if cur is None:
        yield None
        return
    with cur[0].span(name, **attrs) as rec:
        yield rec


def maybe_event(name: str, **attrs) -> Optional[int]:
    """Ambient-gated instant event (see :func:`maybe_span`)."""
    cur = _CURRENT.get()
    if cur is None:
        return None
    return cur[0].event(name, **attrs)


def export_chrome_trace(path: str,
                        recorder: Optional[SpanRecorder] = None) -> str:
    return (recorder or _global_recorder).export_chrome_trace(path)


def export_jsonl(path: str,
                 recorder: Optional[SpanRecorder] = None) -> str:
    return (recorder or _global_recorder).export_jsonl(path)
