"""apex_tpu.observability — unified telemetry subsystem.

Three layers (docs/observability.md):

1. **Metrics** — :class:`MetricsRegistry` of counters / gauges /
   fixed-bucket histograms for host-side instrumentation, plus
   :class:`DeviceMetrics` for training-step counters that accumulate as
   jnp arrays *inside* the jitted step (zero host syncs per step; one
   explicit fetch at ``flush()``).
2. **Spans/events** — :class:`SpanRecorder` wall-clock ranges layered on
   ``utils.profiler``'s nvtx-parity ranges; exports Chrome-trace JSON
   and a JSONL event log.  PR 6 added request-scoped distributed
   tracing (``new_trace_id`` / thread-correct span parentage /
   ``kind: trace`` records) that the fleet propagates end to end.
3. **Exporters** — schema-versioned JSONL (what ``bench.py`` emits),
   Prometheus text exposition, Chrome trace.

Plus the **flight recorder** (PR 6): :class:`EventRing`, a bounded
ring of operational transitions (breaker/failover/drain/stall/scaler
skips) dumpable on fault, and ``steptime``, the blocked-fetch
step-time attribution harness (compute vs per-level comm time,
``overlap_fraction``) behind ``bench.py --comm``.

And the **cost model** (PR 8): ``costmodel``, the XLA-calibrated
analytic FLOPs/bytes model over jaxprs (valid-position conv counting,
DCE, per-dtype matmul breakdowns, the documented ``PEAK_FLOPS`` table
and ``mfu()`` fields on every bench train record), and ``memory``,
the compiled memory plans / static liveness / live-array gauges
behind ``peak_bytes`` gating, ``kind: memory`` records, and the
``flop-accounting`` / ``memory-budget`` lint rules.

And **numerics** (PR 9): ``numerics``, device-resident gradient-health
telemetry (per-layer/per-bucket nonfinite counts, abs-max, grad norm,
underflow fraction at the current loss scale), overflow attribution
(a skipped step's flight-ring event names the culprit layer), bf16
DCN-hop quantization-error accounting, and the one-psum cross-replica
divergence digest — all in-graph with zero host syncs (the
``numerics`` lint rule pins it) behind ``kind: numerics`` records and
``bench.py --numerics``.

And **device-time truth** (PR 13): ``timeline``, the stdlib-only
Chrome-trace parser over what ``jax.profiler.start_trace`` already
writes — per-step device busy time, per-kernel top-k, compute vs
collective vs gap split, and a *measured* ``overlap_fraction`` from
actual kernel-interval overlap (the device-timeline counterpart of
``steptime``'s host differencing, cross-checked by
``steptime.timeline_consistency``); ``kind: profile`` records (schema
v8) behind ``bench.py --profile`` and the server's on-demand
``/profilez`` capture; plus the serving KV fragmentation ledger
(``Engine.kv_fragmentation`` / ``kv_waste_bytes`` — ROADMAP item 1's
needle).

And the **compilation plane** (PR 15): ``compilation``, the
in-process trace/compile ledger over every instrumented jit entry —
abstract argument signatures, wall durations, persistent-cache
hit/miss attribution via ``jax.monitoring``, and a retrace-cause
differ that names *which argument's* shape/dtype/static value changed
between two traces of one entry.  Serving engines and the fleet route
their jits through it, giving the zero-retrace steady-state contract
(warmed engines / failover survivors add exactly 0 traces,
tier-1-pinned), ``Engine.compile_census`` / ``Fleet.warmup``, the
supervisor's ``recompilation_storm`` verdict, the ``/compilez``
endpoint, and bench's schema-v10 ``cold_compile_ms`` /
``compiles_total`` / ``steady_state_retraces`` fields.

And the **operational plane** (PR 10): ``server``, a stdlib
``http.server`` introspection endpoint serving ``/healthz`` /
``/metricsz`` (Prometheus exposition, conformance-tested) /
``/statusz`` / ``/flightz`` / ``/tracez`` off a live registry / ring /
recorder, attachable to an Engine, Fleet, or supervisor with one
``server.serve(...)`` call; and ``supervisor``, the host-side
training-run supervisor consuming each step's already-flushed signals
to detect stall / loss spike / NaN / throughput regression / replica
divergence — zero additions to any jitted step (``wrap_step`` is an
audit-pinned identity), emitting flight-ring events, schema-v5
``kind: run`` records, and an end-of-run report artifact.

Wired consumers: ``serving.Engine``/``Seq2SeqEngine`` (enriched
``stats()``), ``parallel.distributed`` (comm accounting),
``amp`` (loss-scale/skip introspection + ``record_scaler``),
``optimizers`` (grad-norm gauge via ``AmpOptimizer.step`` info),
``data.DataLoader`` (host load/wait times),
``utils.checkpoint``/``checkpoint_orbax`` (save/restore latency +
``checkpoint_saved`` flight events), ``fleet`` (SLO/goodput
accounting), and ``bench.py``.
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      DeviceMetrics, get_registry, set_registry,
                      DEFAULT_LATENCY_BUCKETS)
from .tracing import (SpanRecorder, get_recorder, set_recorder, span,
                      event, export_chrome_trace, export_jsonl,
                      new_trace_id, current_trace, maybe_span,
                      maybe_event)
from .flightrec import EventRing, get_ring, set_ring
from .exporters import (SCHEMA_VERSION, JsonlExporter, prometheus_text,
                        host_info, validate_bench_record,
                        validate_bench_jsonl)
from .costmodel import Cost, jaxpr_cost, peak_flops, mfu
from .memory import (memory_plan, jaxpr_live_bytes, live_array_bytes,
                     record_live_arrays)
from .numerics import (NumericsMonitor, divergence_check,
                       divergence_digest, digest_comm_plan)
from .compilation import (CompilationLedger, instrumented_jit,
                          diff_signatures, get_ledger, set_ledger)
from .server import ObservabilityServer
from .supervisor import RunSupervisor, SupervisorConfig
from . import metrics
from . import tracing
from . import flightrec
from . import steptime
from . import timeline
from . import exporters
from . import costmodel
from . import memory
from . import numerics
from . import compilation
from . import server
from . import supervisor

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DeviceMetrics",
    "get_registry", "set_registry", "DEFAULT_LATENCY_BUCKETS",
    "SpanRecorder", "get_recorder", "set_recorder", "span", "event",
    "export_chrome_trace", "export_jsonl",
    "new_trace_id", "current_trace", "maybe_span", "maybe_event",
    "EventRing", "get_ring", "set_ring",
    "SCHEMA_VERSION", "JsonlExporter", "prometheus_text", "host_info",
    "validate_bench_record", "validate_bench_jsonl",
    "Cost", "jaxpr_cost", "peak_flops", "mfu",
    "memory_plan", "jaxpr_live_bytes", "live_array_bytes",
    "record_live_arrays",
    "NumericsMonitor", "divergence_check", "divergence_digest",
    "digest_comm_plan",
    "CompilationLedger", "instrumented_jit", "diff_signatures",
    "get_ledger", "set_ledger",
    "ObservabilityServer", "RunSupervisor", "SupervisorConfig",
    "metrics", "tracing", "flightrec", "steptime", "timeline",
    "exporters", "costmodel", "memory", "numerics", "server",
    "supervisor", "compilation",
]
