"""apex_tpu.observability — unified telemetry subsystem.

Three layers (docs/observability.md):

1. **Metrics** — :class:`MetricsRegistry` of counters / gauges /
   fixed-bucket histograms for host-side instrumentation, plus
   :class:`DeviceMetrics` for training-step counters that accumulate as
   jnp arrays *inside* the jitted step (zero host syncs per step; one
   explicit fetch at ``flush()``).
2. **Spans/events** — :class:`SpanRecorder` wall-clock ranges layered on
   ``utils.profiler``'s nvtx-parity ranges; exports Chrome-trace JSON
   and a JSONL event log.
3. **Exporters** — schema-versioned JSONL (what ``bench.py`` emits),
   Prometheus text exposition, Chrome trace.

Wired consumers: ``serving.Engine``/``Seq2SeqEngine`` (enriched
``stats()``), ``parallel.distributed`` (comm accounting),
``amp`` (loss-scale/skip introspection + ``record_scaler``),
``optimizers`` (grad-norm gauge via ``AmpOptimizer.step`` info),
``data.DataLoader`` (host load/wait times), and ``bench.py``.
"""

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      DeviceMetrics, get_registry, set_registry,
                      DEFAULT_LATENCY_BUCKETS)
from .tracing import (SpanRecorder, get_recorder, set_recorder, span,
                      event, export_chrome_trace, export_jsonl)
from .exporters import (SCHEMA_VERSION, JsonlExporter, prometheus_text,
                        host_info, validate_bench_record,
                        validate_bench_jsonl)
from . import metrics
from . import tracing
from . import exporters

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DeviceMetrics",
    "get_registry", "set_registry", "DEFAULT_LATENCY_BUCKETS",
    "SpanRecorder", "get_recorder", "set_recorder", "span", "event",
    "export_chrome_trace", "export_jsonl",
    "SCHEMA_VERSION", "JsonlExporter", "prometheus_text", "host_info",
    "validate_bench_record", "validate_bench_jsonl",
    "metrics", "tracing", "exporters",
]
