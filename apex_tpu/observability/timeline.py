"""Device-time truth: parse the Chrome trace ``jax.profiler`` already
writes and attribute a step's DEVICE time — measured, not inferred.

``observability.steptime`` decomposes a train step by *host wall-clock
differencing* (full step minus compute twin) — the same indirect
methodology Apex's README warns about for comm/compute overlap claims.
FlexLink (arXiv:2510.15882) and the weight-update-sharding paper
(arXiv:2004.13336) both evaluate with per-kernel device timelines; this
module is the in-tree equivalent: a **stdlib-only** parser (gzip +
json; jax is imported lazily and only by the capture helpers) for the
``*.trace.json.gz`` that ``jax.profiler.start_trace`` drops under its
logdir, producing per-step device-time attribution — total device busy
time, per-kernel top-k, compute vs collective vs gap split, and a
*measured* ``overlap_fraction`` from actual kernel-interval overlap.

Trace-format notes (pinned empirically by tests/test_timeline.py on
this container's jax): the capture lands at
``<logdir>/plugins/profile/<session>/<host>.trace.json.gz`` — gzipped
Chrome-trace JSON ``{"traceEvents": [...]}``.  Kernel executions are
``"ph": "X"`` complete events whose ``args`` carry ``hlo_op`` /
``hlo_module``; on XLA:CPU they run on ``tf_XLATfrtCpuClient`` /
``tf_XLAEigen`` threads (so the 8-virtual-device conftest mesh
exercises the whole pipeline in tier-1), on TPU on the
``/device:TPU:*`` process rows — either way the ``hlo_op`` arg is what
separates device kernels from the python tracer's thousands of host
frames.  Timestamps/durations are microseconds.

Two gotchas this module exists to encode:

- **Collectives are classified by kernel name** (``all-reduce`` /
  ``all-gather`` / ``reduce-scatter`` / ``collective-permute`` /
  ``all-to-all`` — the names XLA gives psum/ppermute&co lowerings);
  the pattern list is public so the lint/tests can pin it.
- **Session dirs collide**: ``start_trace`` names its session
  subdirectory by wall-clock *second*, so two captures into one logdir
  within a second silently overwrite each other — which is why
  ``utils.profiler`` now allocates a unique per-capture directory and
  :func:`find_trace_file` insists on exactly resolving the newest
  session under whatever directory it is handed.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = ["COLLECTIVE_PATTERNS", "PROFILE_FIELDS", "classify_kernel",
           "find_trace_file", "load_trace", "device_events",
           "merge_intervals", "overlap_us", "attribute_timeline",
           "analyze_capture", "profile_record", "capture",
           "make_profiler"]

# substrings (lowercase) of HLO kernel names that are cross-device
# communication: XLA lowers psum -> all-reduce, all_gather ->
# all-gather, psum_scatter -> reduce-scatter, ppermute ->
# collective-permute, all_to_all -> all-to-all.  Matched against the
# event name AND its hlo_op so fusion-wrapped collectives
# ("all-reduce-start.1") still classify.
COLLECTIVE_PATTERNS = ("all-reduce", "allreduce", "all-gather",
                       "allgather", "reduce-scatter", "reducescatter",
                       "collective-permute", "collectivepermute",
                       "all-to-all", "alltoall", "collective-broadcast",
                       "psum", "ppermute")

# the timing fields every ``kind: profile`` record must carry
# (exporters.validate_profile_record keys its checks off these; they
# are all in MILLISECONDS except the fraction)
PROFILE_FIELDS = ("span_ms", "device_busy_ms", "compute_ms",
                  "collective_ms", "gap_ms", "overlap_ms",
                  "measured_overlap_fraction")

_TRACE_SUFFIXES = (".trace.json.gz", ".trace.json")


def classify_kernel(name: str) -> str:
    """``"collective"`` or ``"compute"`` for one HLO kernel name."""
    low = str(name).lower()
    for pat in COLLECTIVE_PATTERNS:
        if pat in low:
            return "collective"
    return "compute"


def find_trace_file(logdir: str) -> str:
    """Resolve the trace file of the NEWEST capture session under
    ``logdir`` (a direct ``*.trace.json[.gz]`` path passes through).
    Searches ``logdir`` itself and the ``plugins/profile/<session>/``
    layout ``jax.profiler`` writes; raises ``FileNotFoundError`` when
    no trace file exists — the caller should be handing a unique
    per-capture directory (``utils.profiler.profile()`` yields one), so
    "newest" is normally "the only one"."""
    if os.path.isfile(logdir):
        return logdir
    candidates: List[str] = []
    for root in (logdir, os.path.join(logdir, "plugins", "profile")):
        for path in glob.glob(os.path.join(glob.escape(root), "*")) \
                + glob.glob(os.path.join(glob.escape(root), "*", "*")):
            if os.path.isfile(path) and path.endswith(_TRACE_SUFFIXES):
                candidates.append(path)
    if not candidates:
        raise FileNotFoundError(
            f"no *.trace.json[.gz] under {logdir!r} — was the capture "
            f"stopped (profiler.stop_profile) before parsing?")
    # newest session wins; mtime first, path as the deterministic tie
    return max(candidates, key=lambda p: (os.path.getmtime(p), p))


def load_trace(path: str) -> Dict[str, Any]:
    """Load one Chrome-trace JSON document (gzipped or plain)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError(f"{path}: not a Chrome trace (no traceEvents "
                         f"list)")
    return doc


def device_events(doc: Dict[str, Any],
                  modules: Optional[Iterable[str]] = None
                  ) -> List[Dict[str, Any]]:
    """Extract device kernel executions from one trace document:
    complete (``ph: X``) events whose args carry ``hlo_op`` — the
    python tracer's host frames and the thread-metadata rows never do.
    ``modules`` optionally restricts to events whose ``hlo_module``
    contains any of the given substrings (e.g. ``("jit_step",)`` to
    attribute ONE jitted program and drop the blocked-fetch plumbing
    around it)."""
    mods = tuple(modules) if modules is not None else None
    out: List[Dict[str, Any]] = []
    for e in doc.get("traceEvents", []):
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        args = e.get("args")
        if not isinstance(args, dict):
            continue
        op = args.get("hlo_op")
        if not isinstance(op, str) or not op:
            continue
        module = args.get("hlo_module")
        if mods is not None and not (
                isinstance(module, str)
                and any(m in module for m in mods)):
            continue
        try:
            ts = float(e["ts"])
            dur = float(e.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        name = e.get("name") if isinstance(e.get("name"), str) else op
        kind = classify_kernel(name)
        if kind == "compute":
            kind = classify_kernel(op)
        out.append({"name": name, "op": op, "module": module,
                    "ts": ts, "dur": max(dur, 0.0),
                    "lane": (e.get("pid"), e.get("tid")),
                    "kind": kind})
    return out


def merge_intervals(intervals: Iterable[Tuple[float, float]]
                    ) -> List[Tuple[float, float]]:
    """Union of half-open intervals as a sorted disjoint list."""
    ivs = sorted((s, e) for s, e in intervals if e > s)
    out: List[Tuple[float, float]] = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def overlap_us(a: List[Tuple[float, float]],
               b: List[Tuple[float, float]]) -> float:
    """Total overlap between two MERGED interval lists (two-pointer
    sweep)."""
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


_SUFFIX_RE = re.compile(r"\.\d+$")


def _kernel_base(name: str) -> str:
    """Aggregate key for top-k: strip XLA's ``.N`` instance suffix so
    ``dot.1`` / ``dot.3`` report as one ``dot`` line."""
    return _SUFFIX_RE.sub("", name)


def attribute_timeline(events: List[Dict[str, Any]], top_k: int = 10
                       ) -> Dict[str, Any]:
    """Per-capture device-time attribution over extracted events.

    All times are the UNION over lanes (a kernel running on 8 virtual
    devices at once counts its wall extent once — the schedule view,
    matching what host differencing tries to estimate):

    - ``span_ms``: first kernel start to last kernel end;
    - ``device_busy_ms``: union of all kernel intervals;
    - ``compute_ms`` / ``collective_ms``: per-class unions;
    - ``gap_ms``: ``span - busy`` — scheduling stall / host time
      between kernels;
    - ``overlap_ms``: time covered by BOTH a compute and a collective
      interval — the measured comm/compute overlap;
    - ``measured_overlap_fraction``: ``overlap / collective`` (0.0
      with no collectives) — the device-timeline counterpart of
      ``steptime``'s differenced ``overlap_fraction``.
    """
    comp = merge_intervals((e["ts"], e["ts"] + e["dur"])
                           for e in events if e["kind"] == "compute")
    coll = merge_intervals((e["ts"], e["ts"] + e["dur"])
                           for e in events if e["kind"] == "collective")
    busy = merge_intervals([(s, e) for s, e in comp] +
                           [(s, e) for s, e in coll])
    busy_us = sum(e - s for s, e in busy)
    comp_us = sum(e - s for s, e in comp)
    coll_us = sum(e - s for s, e in coll)
    if busy:
        span_us = (max(e for _, e in busy) - min(s for s, _ in busy))
    else:
        span_us = 0.0
    ovl_us = overlap_us(comp, coll)
    frac = (ovl_us / coll_us) if coll_us > 0 else 0.0

    agg: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for e in events:
        key = (_kernel_base(e["name"]), e["kind"])
        a = agg.setdefault(key, {"name": key[0], "kind": key[1],
                                 "count": 0, "total_us": 0.0})
        a["count"] += 1
        a["total_us"] += e["dur"]
    top = sorted(agg.values(), key=lambda a: -a["total_us"])[:top_k]

    def ms(us):
        return round(us / 1e3, 4)

    return {"span_ms": ms(span_us),
            "device_busy_ms": ms(busy_us),
            "compute_ms": ms(comp_us),
            "collective_ms": ms(coll_us),
            "gap_ms": ms(max(span_us - busy_us, 0.0)),
            "overlap_ms": ms(ovl_us),
            "measured_overlap_fraction": round(min(max(frac, 0.0), 1.0),
                                               4),
            "kernel_count": len(events),
            "lane_count": len({e["lane"] for e in events}),
            "top_kernels": [{"name": a["name"], "kind": a["kind"],
                             "count": a["count"],
                             "total_ms": ms(a["total_us"])}
                            for a in top]}


def analyze_capture(logdir: str,
                    modules: Optional[Iterable[str]] = None,
                    steps: int = 1, top_k: int = 10) -> Dict[str, Any]:
    """Find + parse the capture under ``logdir`` and attribute it.
    ``steps`` divides the time fields (a capture of N identical steps
    reports per-step ms; the fraction and counts stay whole-capture),
    recorded on the result as ``steps``."""
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    path = find_trace_file(logdir)
    att = attribute_timeline(device_events(load_trace(path),
                                           modules=modules),
                             top_k=top_k)
    if steps > 1:
        for k in ("span_ms", "device_busy_ms", "compute_ms",
                  "collective_ms", "gap_ms", "overlap_ms"):
            att[k] = round(att[k] / steps, 4)
        for a in att["top_kernels"]:
            a["total_ms"] = round(a["total_ms"] / steps, 4)
    att["steps"] = steps
    att["trace_path"] = path
    return att


def profile_record(attribution: Dict[str, Any], metric: str,
                   **extra) -> Dict[str, Any]:
    """Shape one attribution as a ``kind: profile`` record body (the
    caller routes it through ``JsonlExporter.enrich`` for the
    envelope); ``extra`` lands verbatim (e.g. ``kv_waste_bytes`` /
    ``kv_utilization`` on serving profiles)."""
    return {"kind": "profile", "metric": metric, **attribution, **extra}


# -- capture helpers (the only jax-touching surface, imported lazily) ----

def _blocked_fetch(out) -> None:
    # the steptime barrier discipline: a D2H fetch cannot complete
    # before the dispatched program finishes, so every kernel the
    # window dispatched lands INSIDE the window
    from .steptime import _block
    _block(out)


def capture(fn: Callable, *args, iters: int = 1,
            logdir: Optional[str] = None,
            modules: Optional[Iterable[str]] = None,
            top_k: int = 10) -> Dict[str, Any]:
    """Run ``fn(*args)`` ``iters`` times inside a fresh profiler window
    (unique per-capture directory via ``utils.profiler.profile``) with
    a blocked fetch before the window closes, then parse and return the
    per-step attribution.  The caller should have warmed/compiled
    ``fn`` first — a cold call captures the compile, not the step."""
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    from ..utils import profiler
    out = None
    with profiler.profile(*(() if logdir is None else (logdir,))) as cap:
        for _ in range(iters):
            out = fn(*args)
        _blocked_fetch(out)
    return analyze_capture(cap, modules=modules, steps=iters,
                           top_k=top_k)


def make_profiler(subject: str = "live_process",
                  default_duration_ms: float = 250.0,
                  max_duration_ms: float = 2000.0,
                  logdir: Optional[str] = None,
                  top_k: int = 10,
                  cleanup: bool = True) -> Callable:
    """Build the on-demand capture hook ``/profilez`` calls: a
    one-optional-arg callable that opens a BOUNDED profiler window on
    the live process (whatever the serving/training loop dispatches
    during it is what gets attributed), parses it, and returns the
    ``kind: profile`` record body.  Raises
    ``server.ProfileInFlight`` when a trace window is already open
    (ours or a foreign ``start_trace``), which the endpoint maps to
    HTTP 409.  ``cleanup=True`` (the default here, unlike bench/test
    captures whose dirs are the artifact) deletes the capture
    directory after parsing — a monitor scraping ``/profilez``
    periodically must not grow /tmp without bound."""
    if max_duration_ms <= 0 or default_duration_ms <= 0:
        raise ValueError("durations must be > 0")

    def _capture(duration_ms: Optional[float] = None) -> Dict[str, Any]:
        import shutil
        import time as _time

        from ..utils import profiler
        from .server import ProfileInFlight
        if profiler.profiling_active():
            raise ProfileInFlight(
                "a profiler trace window is already open in this "
                "process")
        want = float(duration_ms) if duration_ms is not None \
            else float(default_duration_ms)
        if want != want:                   # NaN: the clamp would pass it
            raise ValueError("duration_ms must be a finite number")
        bounded = min(max(want, 1.0), float(max_duration_ms))
        try:
            with profiler.profile(
                    *(() if logdir is None else (logdir,))) as cap:
                _time.sleep(bounded / 1e3)
        except RuntimeError as e:
            # a foreign trace raced us between the check and the start
            raise ProfileInFlight(str(e)) from e
        if profiler.profiling_active():
            # an in-library window opened between the check and our
            # profile(): we JOINED it (refcount semantics), our stop
            # was a no-op, and no trace file exists yet — that is an
            # in-flight capture, not a parse error.  ``cap`` is the
            # OUTER window's directory here: never delete it.
            raise ProfileInFlight(
                "the capture window joined another profile() in "
                "flight; retry once it closes")
        try:
            att = analyze_capture(cap, top_k=top_k)
        except FileNotFoundError as e:
            # the window was ours and closed, yet no trace file —
            # treat as a racing capture; the dir holds nothing worth
            # keeping either way
            if cleanup:
                shutil.rmtree(cap, ignore_errors=True)
            raise ProfileInFlight(str(e)) from e
        except Exception:
            # malformed trace & co: don't leak the capture dir on the
            # way to the 500
            if cleanup:
                shutil.rmtree(cap, ignore_errors=True)
            raise
        if cleanup:
            att.pop("trace_path", None)    # about to dangle
            shutil.rmtree(cap, ignore_errors=True)
        return profile_record(att, metric=subject,
                              duration_ms=round(bounded, 3))

    return _capture
