"""Analytic cost model over jaxprs: FLOPs, transcendentals, and bytes.

This is the machine-checked version of the hand-rolled roofline math in
``artifacts/ROOFLINE_r5.md`` / ``artifacts/step_probe.py`` (which now
import it instead of re-deriving conv FLOPs ad hoc): walk a traced
jaxpr, count the arithmetic each primitive performs, and report totals
plus per-primitive / per-dtype breakdowns.  ``bench.py`` turns the
totals into ``mfu`` / ``achieved_tflops`` fields on every train-step
record, ``analysis.EntryPoint.cost()`` caches one per entry point, and
``analysis.rules.FlopAccountingRule`` budgets them.

The op-cost table deliberately mirrors XLA's ``HloCostAnalysis`` (the
engine behind ``Compiled.cost_analysis()``), calibrated primitive by
primitive against ``jax.stages.Lowered.cost_analysis()`` on this jax
version — so the analytic counts can be cross-validated against XLA's
own counts (tests/test_costmodel.py pins the resnet18 O2 and GPT O2
entry points within 5%, the way tests/test_remat.py already consumes
``cost_analysis()``).  Known, documented divergences:

- **scan**: XLA lowers scan to ``while`` and counts the body ONCE; the
  honest cost of a K-tick decode window is K bodies.  Default mode
  multiplies by the trace-time trip count; ``xla_parity=True`` counts
  once, for cross-validation.
- **cond**: one branch executes; honest mode costs the max branch,
  parity mode sums branches (XLA counts every computation it lowered).
- **while**: the trip count is unknowable statically — the body is
  counted once in both modes and ``Cost.while_loops`` records how many
  loops were so truncated.
- **cumsum**: XLA's reduce-window lowering scores O(n^2); the analytic
  model charges the honest O(n).

Do NOT cross-validate against ``Compiled.cost_analysis()`` on graphs
holding the flat-buffer optimizer: XLA's *post-fusion* counter bills a
fusion's producer instructions at full shape into every consumer, so
the 62 per-leaf ``rebuild`` slices of the flat Adam buffer each
re-count the whole 11M-element update (~8x overcount on the resnet18
step).  ``Lowered.cost_analysis()`` (pre-optimization, structurally
1:1 with the jaxpr) is the sane cross-check there; post-optimization
counts are only meaningful on fusion-free-producer graphs like the
fwd+bwd cores test_remat pins.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

__all__ = ["Cost", "jaxpr_cost", "eqn_flops", "conv_flops", "dot_flops",
           "PEAK_FLOPS", "peak_flops", "mfu", "xla_cost"]


# -- peak-FLOPs table ------------------------------------------------------
#
# Per-chip peak arithmetic rates by ``jax.devices()[0].device_kind``
# (substring-matched, case-insensitive) and matmul operand dtype.
# Sources:
#  - TPU v5-lite (v5e): 197 bf16 TFLOP/s, 394 int8 TOP/s per chip
#    (public v5e spec; the value artifacts/ROOFLINE_r5.md's 11.4%-MFU
#    headline was derived against).  fp32 has no published MXU rate;
#    ~1/4 of bf16 is the engineering estimate used for fp32 matmuls.
#  - cpu: a NOMINAL 100 GFLOP/s smoke constant.  CPU-host MFU is not a
#    hardware statement — the constant exists so CPU smoke rounds
#    produce comparable mfu columns round-to-round (the same reason
#    CPU timings warn rather than gate in check_bench_trend.py).
PEAK_FLOPS: Dict[str, Dict[str, float]] = {
    "tpu v5 lite": {"bfloat16": 197e12, "float32": 49.25e12,
                    "int8": 394e12},
    "tpu v5e": {"bfloat16": 197e12, "float32": 49.25e12,
                "int8": 394e12},
    "cpu": {"bfloat16": 100e9, "float32": 100e9, "float64": 50e9},
}


def peak_flops(arch: str, dtype: str) -> Optional[float]:
    """Peak FLOP/s for a device kind + matmul dtype, or None when the
    table has no entry (unknown hardware must not fabricate an MFU)."""
    a = str(arch).lower()
    for key, rates in PEAK_FLOPS.items():
        if key in a or a in key:
            return rates.get(str(dtype))
    return None


def mfu(flops_per_step: float, step_seconds: float, arch: str,
        dtype: str) -> Dict[str, Any]:
    """Model-FLOPs-utilization fields for a bench record.

    ``achieved_tflops`` is always computable; ``mfu`` and
    ``peak_tflops`` are None when the peak table has no entry for the
    hardware (absent beats fabricated)."""
    achieved = flops_per_step / max(step_seconds, 1e-12)
    peak = peak_flops(arch, dtype)
    return {
        "achieved_tflops": achieved / 1e12,
        "peak_tflops": (peak / 1e12) if peak else None,
        "mfu": (achieved / peak) if peak else None,
        "mfu_dtype": str(dtype),
    }


# -- per-eqn FLOP counting -------------------------------------------------

def _nelem(v) -> int:
    return int(np.prod(v.aval.shape)) if hasattr(v, "aval") else 0


def _nbytes(v) -> int:
    if not (hasattr(v, "aval") and hasattr(v.aval, "shape")):
        return 0
    return _nelem(v) * np.dtype(v.aval.dtype).itemsize


def conv_flops(eqn) -> float:
    """XLA ``HandleConvolution`` parity: 2 FMAs per *valid* (output
    position, kernel tap) pair — taps landing in padding or in the
    holes of a dilated input are not arithmetic and are not counted
    (this is why a strided conv's dgrad costs the same as its forward,
    not kernel-size times more — the trap the old hand-rolled
    ``2*B*H*W*Cout*Cin*k^2`` counters fell into on backward graphs).
    Validity factorizes per spatial dimension, so the count is a
    product of per-dimension tallies."""
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    out = eqn.outvars[0].aval
    p = eqn.params
    dn = p["dimension_numbers"]
    strides = p["window_strides"]
    pad = p["padding"]
    lhs_dil = p.get("lhs_dilation") or (1,) * len(strides)
    rhs_dil = p.get("rhs_dilation") or (1,) * len(strides)
    fg = p.get("feature_group_count", 1)
    bg = p.get("batch_group_count", 1)
    batch = lhs.shape[dn.lhs_spec[0]]
    cin = lhs.shape[dn.lhs_spec[1]]
    cout = out.shape[dn.out_spec[1]]
    valid = 1
    for i, d in enumerate(dn.lhs_spec[2:]):
        n = lhs.shape[d]
        k = rhs.shape[dn.rhs_spec[2:][i]]
        s = strides[i]
        plo = pad[i][0]
        bd = lhs_dil[i]
        wd = rhs_dil[i]
        o = out.shape[dn.out_spec[2:][i]]
        span = (n - 1) * bd
        cnt = 0
        for ki in range(k):
            # output positions where tap ki lands on a real element:
            # pos = oi*s + ki*wd - plo in [0, span] and pos % bd == 0
            for oi in range(o):
                pos = oi * s + ki * wd - plo
                if 0 <= pos <= span and pos % bd == 0:
                    cnt += 1
        valid *= cnt
    return 2.0 * batch * cout * (cin // fg) * valid / max(bg, 1)


def dot_flops(eqn) -> float:
    """2*M*N*K (batch dims included in the output element count)."""
    (lc, _rc), _batch = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    k = int(np.prod([lhs.shape[i] for i in lc])) if lc else 1
    return 2.0 * _nelem(eqn.outvars[0]) * k


# one flop per output element (XLA elementwise default; convert and
# compare count too — calibrated against Lowered.cost_analysis())
_ELEMENTWISE_1 = frozenset({
    "add", "sub", "mul", "div", "max", "min", "neg", "abs", "sign",
    "floor", "ceil", "round", "and", "or", "xor", "not",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "convert_element_type", "bitcast_convert_type", "reduce_precision",
    "eq", "ne", "lt", "le", "gt", "ge", "is_finite", "select_n",
    "sqrt_inv", "square", "add_any", "nextafter", "population_count",
    "clz", "real", "imag", "conj",
})
# sqrt/rsqrt et al are transcendentals in XLA's ledger, not flops
_TRANSCENDENTAL = frozenset({
    "exp", "exp2", "expm1", "log", "log1p", "tanh", "sin", "cos",
    "tan", "asin", "acos", "atan", "atan2", "sinh", "cosh", "asinh",
    "acosh", "atanh", "erf", "erfc", "erf_inv", "cbrt", "sqrt",
    "rsqrt", "pow", "digamma", "lgamma", "regularized_incomplete_beta",
    "igamma", "igammac",
})
_ELEMENTWISE_N = {"rem": 8, "clamp": 2}  # calibrated composites
# pure data movement / addressing: no arithmetic
_FREE = frozenset({
    "broadcast_in_dim", "reshape", "transpose", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "rev", "squeeze", "expand_dims", "copy", "stop_gradient", "iota",
    "gather", "scatter", "sort", "split", "device_put",
    "random_seed", "random_wrap", "random_unwrap", "rng_bit_generator",
    "axis_index", "pvary", "sharding_constraint", "iota_32x2_shape",
    "broadcast", "empty", "real_part", "create_token", "optimization_barrier",
})
# collectives: XLA charges the reduction adds (one per payload element
# for psum/pmax/pmin); pure-movement collectives are free
_COLLECTIVE_REDUCE = frozenset({"psum", "pmax", "pmin", "pmean",
                                "reduce_scatter", "psum_scatter"})
_COLLECTIVE_FREE = frozenset({"all_gather", "all_to_all", "ppermute",
                              "pgather", "pbroadcast"})
_REDUCES = frozenset({"reduce_sum", "reduce_max", "reduce_min",
                      "reduce_prod", "reduce_and", "reduce_or",
                      "reduce_xor"})
# everything eqn_flops prices deliberately; anything else lands in
# Cost.unknown_prims (priced at the 1-flop/elem elementwise default)
# so table gaps surface in records instead of hiding
_KNOWN_PRIMS = (_ELEMENTWISE_1 | _TRANSCENDENTAL | _FREE
                | _COLLECTIVE_REDUCE | _COLLECTIVE_FREE | _REDUCES
                | frozenset(_ELEMENTWISE_N)
                | frozenset({
                    "dot_general", "conv_general_dilated", "argmax",
                    "argmin", "cumsum", "cumprod", "cummax", "cummin",
                    "cumlogsumexp", "reduce_window", "reduce_window_sum",
                    "reduce_window_max", "reduce_window_min",
                    "select_and_scatter_add", "integer_pow", "logistic",
                    "threefry2x32", "random_bits", "random_gamma",
                    "random_fold_in", "scatter-add", "scatter-mul",
                    "scatter-min", "scatter-max", "scatter_add",
                    "scatter_mul",
                }))


def eqn_flops(eqn) -> Tuple[float, float]:
    """(flops, transcendentals) of one leaf eqn (no sub-jaxprs)."""
    name = eqn.primitive.name
    if name == "dot_general":
        return dot_flops(eqn), 0.0
    if name == "conv_general_dilated":
        return conv_flops(eqn), 0.0
    if name in _REDUCES:
        return float(max(sum(map(_nelem, eqn.invars))
                         - sum(map(_nelem, eqn.outvars)), 0)), 0.0
    if name in ("argmax", "argmin"):
        # variadic reduce with a ~9-op comparator (calibrated)
        n_in = _nelem(eqn.invars[0])
        n_out = _nelem(eqn.outvars[0])
        return 9.0 * max(n_in - n_out, 0), 0.0
    if name in ("cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp"):
        # honest O(n); XLA's reduce-window lowering would say O(n^2)
        return float(_nelem(eqn.outvars[0])), 0.0
    if name == "reduce_window_sum" or name == "reduce_window":
        win = int(np.prod(eqn.params.get("window_dimensions", (1,))))
        return float(_nelem(eqn.outvars[0]) * max(win - 1, 0)), 0.0
    if name in ("reduce_window_max", "reduce_window_min"):
        win = int(np.prod(eqn.params.get("window_dimensions", (1,))))
        return float(_nelem(eqn.outvars[0]) * max(win - 1, 0)), 0.0
    if name == "select_and_scatter_add":
        win = int(np.prod(eqn.params.get("window_dimensions", (1,))))
        return float(_nelem(eqn.outvars[0]) * win), 0.0
    if name == "integer_pow":
        p = abs(int(eqn.params.get("y", 2)))
        if p <= 1:
            return float(_nelem(eqn.outvars[0])), 0.0
        muls = int(np.floor(np.log2(p))) + bin(p).count("1") - 1
        return float(_nelem(eqn.outvars[0]) * muls), 0.0
    if name == "logistic":
        n = _nelem(eqn.outvars[0])
        return 3.0 * n, float(n)
    if name in _TRANSCENDENTAL:
        return 0.0, float(_nelem(eqn.outvars[0]))
    if name in _ELEMENTWISE_N:
        return float(_nelem(eqn.outvars[0]) * _ELEMENTWISE_N[name]), 0.0
    if name in _COLLECTIVE_REDUCE:
        return float(sum(map(_nelem, eqn.invars))), 0.0
    if name in _COLLECTIVE_FREE or name in _FREE:
        return 0.0, 0.0
    if name in ("scatter-add", "scatter-mul", "scatter-min",
                "scatter-max", "scatter_add", "scatter_mul"):
        # combining scatters do one op per update element; plain
        # "scatter" (at[].set) is movement and sits in _FREE
        ups = eqn.invars[2] if len(eqn.invars) > 2 else eqn.invars[-1]
        return float(_nelem(ups)), 0.0
    if name in ("threefry2x32", "random_bits"):
        # counter-based PRNG rounds (calibrated ~18-20 ops/element on
        # the lowered module; only sampling/dropout graphs carry these)
        return 18.0 * float(sum(map(_nelem, eqn.outvars))), 0.0
    if name in _ELEMENTWISE_1:
        return float(_nelem(eqn.outvars[0])), 0.0
    # unknown primitive: charge one flop per output element (the
    # elementwise default XLA applies) and record it so a census can
    # surface table gaps instead of silently mispricing them
    return float(sum(map(_nelem, eqn.outvars))), 0.0


# -- whole-graph accounting ------------------------------------------------

@dataclass
class Cost:
    """Analytic cost of one traced graph (totals are per device for a
    shard_map'd program: the body is the per-device program)."""
    flops: float = 0.0
    transcendentals: float = 0.0
    bytes_accessed: int = 0            # operand + result bytes, all eqns
    matmul_flops: float = 0.0          # dot_general + conv flops only
    flops_by_prim: Dict[str, float] = field(default_factory=dict)
    matmul_flops_by_dtype: Dict[str, float] = field(default_factory=dict)
    bytes_by_dtype: Dict[str, int] = field(default_factory=dict)
    eqns: int = 0
    while_loops: int = 0               # bodies counted once (trip unknown)
    unknown_prims: Dict[str, int] = field(default_factory=dict)

    @property
    def dominant_matmul_dtype(self) -> Optional[str]:
        """Operand dtype carrying the most dot/conv flops — the dtype
        whose peak rate an MFU figure should be quoted against."""
        if not self.matmul_flops_by_dtype:
            return None
        return max(self.matmul_flops_by_dtype,
                   key=self.matmul_flops_by_dtype.get)

    def fp32_matmul_fraction(self) -> float:
        """Fraction of dot/conv flops with fp32 operands — the silent
        O2-upcast signal the FlopAccountingRule budgets."""
        if not self.matmul_flops:
            return 0.0
        return self.matmul_flops_by_dtype.get("float32", 0.0) \
            / self.matmul_flops

    def to_record(self) -> Dict[str, Any]:
        """Flat JSONL payload (enriched + kind-tagged by callers)."""
        rec = {
            "flops": self.flops,
            "transcendentals": self.transcendentals,
            "bytes_accessed": int(self.bytes_accessed),
            "matmul_flops": self.matmul_flops,
            "matmul_flops_by_dtype": dict(self.matmul_flops_by_dtype),
            "bytes_by_dtype": {k: int(v)
                               for k, v in self.bytes_by_dtype.items()},
            "eqns": int(self.eqns),
        }
        if self.while_loops:
            rec["while_loops"] = int(self.while_loops)
        if self.unknown_prims:
            rec["unknown_prims"] = dict(self.unknown_prims)
        return rec


def _live_eqns(jx):
    """Backward DCE sweep: eqns whose outputs are (transitively) unused
    and that carry no effects never execute — XLA prunes them before
    lowering, so counting them would overstate the step (the classic
    case: an entry point's step drops the info dict, killing the whole
    grad-norm chain)."""
    import jax.extend.core
    needed = {id(v) for v in jx.outvars
              if isinstance(v, jax.extend.core.Var)}
    keep = [False] * len(jx.eqns)
    for i in range(len(jx.eqns) - 1, -1, -1):
        eqn = jx.eqns[i]
        if getattr(eqn, "effects", None) or any(
                id(v) in needed for v in eqn.outvars):
            keep[i] = True
            for v in eqn.invars:
                if isinstance(v, jax.extend.core.Var):
                    needed.add(id(v))
    return [e for e, k in zip(jx.eqns, keep) if k]


def _subjaxprs(eqn):
    import jax
    import jax.extend.core
    kinds = (jax.extend.core.Jaxpr, jax.extend.core.ClosedJaxpr)
    out = []
    for v in eqn.params.values():
        for s in jax.tree_util.tree_leaves(
                v, is_leaf=lambda x: isinstance(x, kinds)):
            if isinstance(s, kinds):
                out.append(s)
    return out


def jaxpr_cost(jaxpr, xla_parity: bool = False) -> Cost:
    """Analytic :class:`Cost` of a (closed) jaxpr.

    Default mode is the honest execution cost: scan bodies multiply by
    their trace-time trip count, cond costs its most expensive branch.
    ``xla_parity=True`` switches both to what ``HloCostAnalysis``
    counts on the lowered-but-unoptimized module (scan body once, cond
    branches summed) for cross-validation against
    ``Lowered.cost_analysis()``."""
    import jax.extend.core
    cost = Cost()

    def accumulate(jx, mult):
        if isinstance(jx, jax.extend.core.ClosedJaxpr):
            jx = jx.jaxpr
        for eqn in _live_eqns(jx):
            name = eqn.primitive.name
            if name == "scan":
                length = 1 if xla_parity else eqn.params.get("length", 1)
                accumulate(eqn.params["jaxpr"], mult * length)
                continue
            if name == "while":
                cost.while_loops += 1
                accumulate(eqn.params["body_jaxpr"], mult)
                accumulate(eqn.params["cond_jaxpr"], mult)
                continue
            if name == "cond":
                branches = eqn.params["branches"]
                if xla_parity:
                    for b in branches:
                        accumulate(b, mult)
                else:
                    best, best_cost = None, -1.0
                    for b in branches:
                        sub = jaxpr_cost(b, xla_parity=False)
                        if sub.flops > best_cost:
                            best, best_cost = b, sub.flops
                    if best is not None:
                        accumulate(best, mult)
                continue
            subs = _subjaxprs(eqn)
            if subs:
                for s in subs:
                    accumulate(s, mult)
                continue
            f, t = eqn_flops(eqn)
            cost.flops += mult * f
            cost.transcendentals += mult * t
            cost.eqns += 1
            if f:
                cost.flops_by_prim[name] = \
                    cost.flops_by_prim.get(name, 0.0) + mult * f
            if name in ("dot_general", "conv_general_dilated"):
                cost.matmul_flops += mult * f
                dt = str(eqn.invars[0].aval.dtype)
                cost.matmul_flops_by_dtype[dt] = \
                    cost.matmul_flops_by_dtype.get(dt, 0.0) + mult * f
            if name not in _KNOWN_PRIMS:
                cost.unknown_prims[name] = \
                    cost.unknown_prims.get(name, 0) + 1
            for v in list(eqn.invars) + list(eqn.outvars):
                b = _nbytes(v)
                if b:
                    cost.bytes_accessed += int(mult * b)
                    dt = str(v.aval.dtype)
                    cost.bytes_by_dtype[dt] = \
                        cost.bytes_by_dtype.get(dt, 0) + int(mult * b)

    accumulate(jaxpr, 1.0)
    return cost


def xla_cost(stage) -> Dict[str, float]:
    """Normalize ``Lowered.cost_analysis()`` / ``Compiled.
    cost_analysis()`` output (list-wrapped on some jax versions) to a
    flat dict with at least ``flops``/``transcendentals`` keys."""
    ca = stage.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    out = dict(ca)
    out.setdefault("flops", 0.0)
    out.setdefault("transcendentals", 0.0)
    return out
