"""Numerics observability: device-resident gradient-health telemetry,
overflow attribution, and cross-replica divergence digests.

The dynamic loss scaler (amp/scaler.py) is this repo's identity, yet a
skipped step used to say only *that* something overflowed — never
*where*; nothing could detect a silently diverged replica; and the
PR 5 bf16 DCN-hop compression reported its wire savings but not what
the quantization actually loses.  This module closes all three gaps
with the same contract PR 1's :class:`~.metrics.DeviceMetrics`
established: every per-step quantity is accumulated as jnp arrays
*inside* the jitted step (zero host syncs — pinned by the ``numerics``
lint rule and tests/test_step_graph_audit.py), and :meth:`flush` is
the single explicit ``jax.device_get``.

Three instruments, one monitor:

- **Per-layer gradient health** (:meth:`NumericsMonitor.update` with
  ``grad_stats`` from ``AmpOptimizer.step(grad_health=...)``):
  nonfinite counts, abs-max, grad norm, and the *underflow fraction* —
  the share of nonzero gradient elements whose scaled magnitude falls
  below the half dtype's smallest normal (``finfo(half).tiny``), i.e.
  exactly what the **current** loss scale fails to protect.  The layer
  with the most nonfinite elements on an overflowed step is the
  **culprit** a skipped step's flight-ring event names.
- **Per-bucket stats + compression error** (``bucket_stats`` from
  ``allreduce_grads_tree(numerics_out=...)``): the stats ride the
  existing DDP bucket structure, and the bf16 DCN hop reports the
  squared quantization error of each replica's own shard — the cost
  side of the PR 5 wire savings (arXiv:2004.13336).
- **Cross-replica divergence digest** (``sync_tree``): a cheap
  per-leaf checksum ``[sum(x), sum(x^2)]`` whose single ``psum``
  satisfies ``psum(digest) == axis_size * local`` on every replica iff
  the replicas hold identical values — a silently desynced replica
  trips it within one step.  The one extra collective is planned by
  :func:`digest_comm_plan` so the collective-accounting lint rule
  stays exact.

``enabled=False`` is a hard off-switch: :meth:`init` returns an empty
pytree and every mutator is an identity, so a numerics-disabled step
traces to a **byte-identical** jaxpr (the other half of the ``numerics``
lint rule).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["NumericsMonitor", "leaf_names", "bucket_labels",
           "stack_bucket_stats", "divergence_digest", "divergence_check",
           "digest_comm_plan", "DEFAULT_DIGEST_TOL"]

# relative deviation above which the digest declares a replica desynced.
# Replicated state is bitwise identical across replicas, so the psum of
# identical digests differs from ``world * local`` only by the rounding
# of the reduction order — zero for power-of-two worlds (repeated exact
# doubling), a few ulps otherwise.  1e-6 is ~100x that noise floor and
# ~1000x below any real divergence (one perturbed fp32 element moves
# the digest by its own magnitude).
DEFAULT_DIGEST_TOL = 1e-6


def _path_str(path) -> str:
    """'/'-joined readable key path (local twin of the helper in
    parallel/distributed.py — duplicated so observability never imports
    the parallel package at module scope)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        else:
            parts.append(str(k))
    return "/".join(parts)


def leaf_names(tree: Any) -> Tuple[str, ...]:
    """'/'-joined key path per leaf, in tree order — the layer labels
    the monitor and its flushed records use."""
    import jax
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return tuple(_path_str(p) for p, _ in flat)


def bucket_labels(plan: Sequence[Dict[str, Any]]) -> Tuple[str, ...]:
    """Stable labels for the buckets of one
    :func:`parallel.allreduce_comm_plan` — the runtime's
    ``numerics_out`` entries arrive in the same (dtype-group, bucket)
    order, so position ``i`` of the plan IS position ``i`` of the
    stats."""
    return tuple(f"{b['dtype']}/b{i}" for i, b in enumerate(plan))


def stack_bucket_stats(numerics_out: Sequence[Dict[str, Any]]
                       ) -> Dict[str, Any]:
    """Stack the per-bucket device scalars of one
    ``allreduce_grads_tree(numerics_out=...)`` call into ``(B,)``
    arrays (``compression_sq_error`` defaults to 0 for uncompressed
    buckets)."""
    import jax.numpy as jnp
    zero = jnp.zeros((), jnp.float32)
    return {
        "nonfinite": jnp.stack([b["nonfinite"] for b in numerics_out]),
        "abs_max": jnp.stack([b["abs_max"] for b in numerics_out]),
        "sq_sum": jnp.stack([b["sq_sum"] for b in numerics_out]),
        "compression_sq_error": jnp.stack(
            [b.get("compression_sq_error", zero) for b in numerics_out]),
    }


# -- divergence digest ------------------------------------------------------

def divergence_digest(tree: Any):
    """Per-leaf ``[sum(x), sum(x*x)]`` checksum, fp32, shape ``(L, 2)``.
    Replicas computing the same program on the same state produce
    bitwise-identical digests — any drift (a dropped collective, a
    corrupted buffer, a rank applying a different update) moves at
    least one component."""
    import jax
    import jax.numpy as jnp
    rows = []
    for leaf in jax.tree_util.tree_leaves(tree):
        x = leaf.astype(jnp.float32).reshape(-1)
        rows.append(jnp.stack([jnp.sum(x), jnp.sum(x * x)]))
    return jnp.stack(rows)


def divergence_check(tree: Any, axis_name: str,
                     tol: float = DEFAULT_DIGEST_TOL) -> Dict[str, Any]:
    """One-collective replica-sync check: ``psum`` the per-leaf digest
    over ``axis_name`` and compare against ``axis_size * local`` —
    equality (within ``tol`` relative) on every replica means every
    replica holds the same values.  Must run inside the mapped context.

    Returns device values: ``rel`` ``(L,)`` per-leaf relative
    deviation, ``max_rel_dev`` scalar, and ``in_sync`` (fp32 0/1).
    All ops beyond the single ``psum`` are local — the collective
    census of an instrumented step grows by exactly the one eqn
    :func:`digest_comm_plan` budgets."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    d = divergence_digest(tree)
    world = int(lax.axis_size(axis_name))
    tot = lax.psum(d, axis_name)
    dev = jnp.abs(tot - world * d)
    denom = jnp.abs(tot) + world * jnp.abs(d) + 1e-30
    rel = jnp.max(dev / denom, axis=1)            # (L,)
    # a nonfinite digest (a replica whose state holds NaN/inf) is
    # maximal divergence, not un-measurable: clamp to 1.0, the upper
    # bound of dev/denom for finite inputs, so the flush stays
    # JSON-clean and the desync counter still trips
    rel = jnp.where(jnp.isfinite(rel), rel, 1.0)
    max_rel = jnp.max(rel)
    return {"rel": rel, "max_rel_dev": max_rel,
            "in_sync": (max_rel <= tol).astype(jnp.float32)}


def digest_comm_plan(tree: Any) -> List[Dict[str, Any]]:
    """Static plan of :func:`divergence_check`'s collectives — ONE psum
    of the ``(L, 2)`` fp32 digest.  Shaped like an
    ``allreduce_comm_plan`` bucket so
    ``parallel.plan_collective_expectations(plan + digest_comm_plan(t))``
    folds it into the collective rule's exact expectations."""
    import jax
    leaves = jax.tree_util.tree_leaves(tree)
    n = 2 * len(leaves)
    b = 4 * n
    return [{
        "dtype": "float32", "comm_dtype": "float32",
        "leaves": len(leaves), "elements": n, "chunks": 1,
        "cause": "numerics_digest", "topology": "flat",
        "wire_elements": n, "padded_elements": 0, "wire_bytes": b,
        "ici_wire_bytes": b, "dcn_wire_bytes": b,
        "dcn_comm_dtype": "float32",
        "eqns": {"psum": 1}, "eqn_payload_bytes": {"psum": b}}]


# -- the monitor ------------------------------------------------------------

class NumericsMonitor:
    """Device-resident numerics accounting for jitted training steps.

    Like :class:`~.metrics.DeviceMetrics`, the state returned by
    :meth:`init` is a flat ``{name: jnp.ndarray}`` pytree that rides
    the step carry; :meth:`update` is pure (state in, new state out)
    and lowers to elementwise math plus at most the one digest psum;
    :meth:`flush` is the single host fetch.

        nm = NumericsMonitor(params, half_dtype="float16",
                             bucket_labels=numerics.bucket_labels(plan),
                             axis_name="data")
        tele = nm.init()
        # inside the jitted step:
        nout = []
        grads = ddp.allreduce_grads(grads, numerics_out=nout)
        params, ost, info = opt.step(params, ost, grads, grad_health=nm)
        tele = nm.update(tele, grad_stats=info.get("grad_health"),
                         bucket_stats=nout,
                         found_inf=info["found_inf"],
                         loss_scale=info["loss_scale"],
                         sync_tree=params)
        # on the host, every N steps:
        flushed = nm.flush(tele)          # ONE device_get
        rec = nm.to_record(flushed, metric="resnet50_o2_ddp")

    ``enabled=False`` turns every method into an identity (``init``
    returns an empty dict, i.e. a pytree with zero leaves), so the
    instrumented step traces to the byte-identical jaxpr of the
    uninstrumented one — the off-switch really is free.
    """

    def __init__(self, grads_like: Any = None,
                 names: Optional[Sequence[str]] = None,
                 half_dtype: Any = "bfloat16",
                 bucket_labels: Optional[Sequence[str]] = None,
                 digest: bool = False,
                 axis_name: Optional[str] = None,
                 digest_tol: float = DEFAULT_DIGEST_TOL,
                 enabled: bool = True,
                 prefix: str = "numerics_",
                 registry=None, ring=None):
        import jax
        import jax.numpy as jnp
        if (grads_like is None) == (names is None):
            raise ValueError("exactly one of grads_like/names required")
        if grads_like is not None:
            self.names = leaf_names(grads_like)
            self.sizes = tuple(
                int(math.prod(l.shape)) if hasattr(l, "shape") else 1
                for l in jax.tree_util.tree_leaves(grads_like))
        else:
            self.names = tuple(names)
            self.sizes = tuple(1 for _ in self.names)
        if not self.names:
            raise ValueError("NumericsMonitor needs at least one layer")
        dt = jnp.dtype({"bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
                        "fp16": jnp.float16, "float16": jnp.float16
                        }.get(half_dtype, half_dtype))
        if dt not in (jnp.dtype(jnp.float16), jnp.dtype(jnp.bfloat16)):
            raise ValueError(f"half_dtype must be fp16/bf16, got {dt}")
        self.half_dtype = dt.name
        # smallest normal of the half dtype: a SCALED gradient below it
        # is what the current loss scale fails to lift into range
        self.tiny = float(jnp.finfo(dt).tiny)
        self.bucket_labels = (tuple(bucket_labels)
                              if bucket_labels else None)
        self.digest = bool(digest)
        self.axis_name = axis_name
        if self.digest and not axis_name:
            raise ValueError("digest=True needs axis_name= (the mapped "
                             "data axis the psum runs over)")
        self.digest_tol = float(digest_tol)
        self.enabled = bool(enabled)
        self.prefix = prefix
        self.registry = registry
        self.ring = ring
        # host-side flush memory for the flight-ring deltas
        self._last_overflow_steps = 0
        self._last_desync_steps = 0

    # -- device state -------------------------------------------------------
    def init(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        if not self.enabled:
            return {}
        L = len(self.names)
        z = lambda *s: jnp.zeros(s, jnp.float32)  # noqa: E731
        state = {
            "steps": z(), "overflow_steps": z(), "grad_steps": z(),
            "loss_scale": z(),
            "nonfinite": z(L), "underflow": z(L), "abs_max": z(L),
            "sq_sum": z(L),
            "culprit_idx": jnp.full((), -1.0, jnp.float32),
            "culprit_nonfinite": z(),
        }
        if self.bucket_labels:
            B = len(self.bucket_labels)
            state.update(bucket_nonfinite=z(B), bucket_abs_max=z(B),
                         bucket_sq_sum=z(B), bucket_comp_err=z(B))
        if self.digest:
            state.update(div_rel=z(L), div_max=z(), desync_steps=z(),
                         div_worst_idx=jnp.full((), -1.0, jnp.float32))
        return state

    def leaf_stats(self, scaled_grads: Any, loss_scale: Any
                   ) -> Dict[str, Any]:
        """Per-leaf health of one gradient tree (``(L,)`` arrays), all
        local elementwise math: ``nonfinite`` counts, ``abs_max`` and
        ``sq_sum`` of the UNSCALED finite values (nonfinite masked to 0
        so one inf cannot poison the magnitudes it sits next to), and
        ``underflow`` — elements whose *scaled* magnitude is a nonzero
        subnormal of the half dtype.  ``AmpOptimizer.step`` calls this
        on the pre-pack gradient tree when handed ``grad_health=``."""
        import jax
        import jax.numpy as jnp
        leaves = jax.tree_util.tree_leaves(scaled_grads)
        if len(leaves) != len(self.names):
            raise ValueError(
                f"gradient tree has {len(leaves)} leaves, monitor was "
                f"built over {len(self.names)}")
        scale = jnp.asarray(loss_scale, jnp.float32)
        nonf, amax, sq, under = [], [], [], []
        for leaf in leaves:
            x = leaf.astype(jnp.float32).reshape(-1)
            fin = jnp.isfinite(x)
            ax = jnp.abs(jnp.where(fin, x, 0.0))
            nonf.append(jnp.sum(~fin).astype(jnp.float32))
            amax.append(jnp.max(ax, initial=0.0) / scale)
            sq.append(jnp.sum(ax * ax) / (scale * scale))
            under.append(jnp.sum(
                (ax > 0) & (ax < self.tiny)).astype(jnp.float32))
        return {"nonfinite": jnp.stack(nonf), "abs_max": jnp.stack(amax),
                "sq_sum": jnp.stack(sq), "underflow": jnp.stack(under)}

    def update(self, state: Dict[str, Any],
               grad_stats: Optional[Dict[str, Any]] = None,
               bucket_stats: Optional[Sequence[Dict[str, Any]]] = None,
               found_inf: Any = None, loss_scale: Any = None,
               sync_tree: Any = None) -> Dict[str, Any]:
        """Fold one step's observations into the device state (pure).

        ``grad_stats``: ``info["grad_health"]`` from
        ``AmpOptimizer.step(grad_health=self)`` (or :meth:`leaf_stats`
        run directly).  ``bucket_stats``: the ``numerics_out`` list one
        ``allreduce_grads_tree`` call filled.  ``found_inf`` decides
        whether this step counts as an overflow (falls back to the
        per-layer nonfinite census).  ``sync_tree`` runs the divergence
        digest — the ONE collective this method may add."""
        if not self.enabled:
            return state
        import jax.numpy as jnp
        st = dict(state)
        st["steps"] = st["steps"] + 1.0
        if loss_scale is not None:
            st["loss_scale"] = jnp.asarray(loss_scale, jnp.float32)
        if grad_stats is not None:
            gs = grad_stats
            # grad_steps, not steps, is the underflow-fraction
            # denominator: a caller folding grad health every other
            # step must not have its fraction diluted by the
            # health-less updates
            st["grad_steps"] = st["grad_steps"] + 1.0
            st["nonfinite"] = st["nonfinite"] + gs["nonfinite"]
            st["underflow"] = st["underflow"] + gs["underflow"]
            st["abs_max"] = jnp.maximum(st["abs_max"], gs["abs_max"])
            st["sq_sum"] = gs["sq_sum"]          # last-step gauge
            has_nonf = jnp.sum(gs["nonfinite"]) > 0
            idx = jnp.argmax(gs["nonfinite"]).astype(jnp.float32)
            st["culprit_idx"] = jnp.where(has_nonf, idx,
                                          st["culprit_idx"])
            st["culprit_nonfinite"] = jnp.where(
                has_nonf, jnp.max(gs["nonfinite"]),
                st["culprit_nonfinite"])
            overflow = has_nonf
        else:
            overflow = jnp.zeros((), jnp.bool_)
        if found_inf is not None:
            overflow = jnp.asarray(found_inf, jnp.float32) > 0
        st["overflow_steps"] = (st["overflow_steps"]
                                + overflow.astype(jnp.float32))
        if bucket_stats is not None:
            if self.bucket_labels is None:
                raise ValueError("bucket_stats given but the monitor "
                                 "was built without bucket_labels")
            if len(bucket_stats) != len(self.bucket_labels):
                raise ValueError(
                    f"{len(bucket_stats)} bucket stats for "
                    f"{len(self.bucket_labels)} labels — derive labels "
                    f"from the same allreduce_comm_plan knobs the "
                    f"runtime uses")
            bs = stack_bucket_stats(bucket_stats)
            st["bucket_nonfinite"] = (st["bucket_nonfinite"]
                                      + bs["nonfinite"])
            st["bucket_abs_max"] = jnp.maximum(st["bucket_abs_max"],
                                               bs["abs_max"])
            st["bucket_sq_sum"] = bs["sq_sum"]   # last-step gauge
            st["bucket_comp_err"] = (st["bucket_comp_err"]
                                     + bs["compression_sq_error"])
        if sync_tree is not None:
            if not self.digest:
                raise ValueError("sync_tree given but the monitor was "
                                 "built with digest=False")
            chk = divergence_check(sync_tree, self.axis_name,
                                   self.digest_tol)
            st["div_rel"] = chk["rel"]
            # pin the worst leaf AT the step that set the running max:
            # div_rel is a last-step gauge, so a replica that desyncs
            # and later re-syncs would otherwise have its flushed
            # worst_leaf point at the final step's noise floor
            worse = chk["max_rel_dev"] > st["div_max"]
            st["div_worst_idx"] = jnp.where(
                worse, jnp.argmax(chk["rel"]).astype(jnp.float32),
                st["div_worst_idx"])
            st["div_max"] = jnp.maximum(st["div_max"],
                                        chk["max_rel_dev"])
            st["desync_steps"] = (st["desync_steps"]
                                  + (1.0 - chk["in_sync"]))
        return st

    # -- host side ----------------------------------------------------------
    def flush(self, state: Dict[str, Any], registry=None
              ) -> Dict[str, Any]:
        """ONE host fetch of the whole state tree.  Folds totals into
        the metrics registry, appends flight-ring events for *new*
        overflow/desync transitions since the previous flush
        (``overflow_attribution`` names the culprit layer;
        ``replica_desync`` carries the worst relative deviation), and
        returns the plain-python summary :meth:`to_record` serializes."""
        import jax
        if not self.enabled:
            return {"enabled": False, "steps": 0, "overflow_steps": 0,
                    "layers": [], "culprit": None}
        host = jax.device_get(state)
        steps = int(host["steps"])
        overflow_steps = int(host["overflow_steps"])
        grad_steps = int(host["grad_steps"])
        layers = []
        for i, name in enumerate(self.names):
            # denominator = elements actually observed: grad_steps
            # counts only the updates that carried grad_stats (a
            # monitor built from names= has unit sizes, so its
            # fraction degrades to a per-observation count — use
            # grads_like for a per-element fraction)
            denom = max(self.sizes[i] * max(grad_steps, 1), 1)
            layers.append({
                "name": name,
                "nonfinite": int(host["nonfinite"][i]),
                "abs_max": float(host["abs_max"][i]),
                "grad_norm": float(host["sq_sum"][i]) ** 0.5,
                "underflow_fraction": min(
                    float(host["underflow"][i]) / denom, 1.0)})
        ci = int(host["culprit_idx"])
        culprit = self.names[ci] if 0 <= ci < len(self.names) else None
        out: Dict[str, Any] = {
            "enabled": True, "steps": steps,
            "overflow_steps": overflow_steps,
            "loss_scale": float(host["loss_scale"]),
            "half_dtype": self.half_dtype, "tiny": self.tiny,
            "grad_norm": float(sum(float(host["sq_sum"][i])
                                   for i in range(len(self.names)))
                               ) ** 0.5,
            "layers": layers, "culprit": culprit,
            "culprit_nonfinite": int(host["culprit_nonfinite"]),
        }
        if self.bucket_labels:
            out["buckets"] = [{
                "label": lbl,
                "nonfinite": int(host["bucket_nonfinite"][i]),
                "abs_max": float(host["bucket_abs_max"][i]),
                "grad_norm": float(host["bucket_sq_sum"][i]) ** 0.5,
                "compression_sq_error":
                    float(host["bucket_comp_err"][i]),
            } for i, lbl in enumerate(self.bucket_labels)]
        if self.digest:
            desync = int(host["desync_steps"])
            wi = int(host["div_worst_idx"])
            out["divergence"] = {
                "max_rel_dev": float(host["div_max"]),
                "desync_steps": desync, "tol": self.digest_tol,
                "in_sync": desync == 0,
                # the leaf AT the step that set max_rel_dev — None
                # until a digest ran (div_rel is only a last-step
                # gauge and must not name the noise floor)
                "worst_leaf": (self.names[wi]
                               if 0 <= wi < len(self.names) else None)}
        self._fold_registry(out, registry)
        self._record_transitions(out)
        return out

    def _fold_registry(self, out, registry):
        from .metrics import get_registry
        reg = registry or self.registry or get_registry()
        p = self.prefix
        reg.counter(p + "overflow_steps_total").set_total(
            out["overflow_steps"])
        reg.gauge(p + "grad_norm").set(out["grad_norm"])
        reg.gauge(p + "loss_scale").set(out["loss_scale"])
        nonf = reg.counter(p + "nonfinite_total")
        amax = reg.gauge(p + "abs_max")
        under = reg.gauge(p + "underflow_fraction")
        for lyr in out["layers"]:
            nonf.labels(layer=lyr["name"]).set_total(lyr["nonfinite"])
            amax.labels(layer=lyr["name"]).set(lyr["abs_max"])
            under.labels(layer=lyr["name"]).set(
                lyr["underflow_fraction"])
        for b in out.get("buckets", ()):
            reg.counter(p + "bucket_nonfinite_total").labels(
                bucket=b["label"]).set_total(b["nonfinite"])
            reg.gauge(p + "compression_sq_error").labels(
                bucket=b["label"]).set(b["compression_sq_error"])
        div = out.get("divergence")
        if div is not None:
            reg.counter(p + "desync_steps_total").set_total(
                div["desync_steps"])
            reg.gauge(p + "divergence_max_rel_dev").set(
                div["max_rel_dev"])

    def _record_transitions(self, out):
        """Flight-ring trail: overflow and desync are the rare,
        diagnostic transitions a post-mortem dump must show next to
        the scaler skips / failovers of the same window.  Dedup is the
        per-monitor flush delta (same truthful-duplicate tradeoff as
        ``amp.record_scaler``)."""
        from . import flightrec
        ring = flightrec.resolve(self.ring)
        if out["overflow_steps"] > self._last_overflow_steps:
            ring.append("overflow_attribution", prefix=self.prefix,
                        culprit=out["culprit"],
                        culprit_nonfinite=out["culprit_nonfinite"],
                        overflow_steps=out["overflow_steps"],
                        loss_scale=out["loss_scale"])
            self._last_overflow_steps = out["overflow_steps"]
        div = out.get("divergence")
        if div is not None and div["desync_steps"] > \
                self._last_desync_steps:
            ring.append("replica_desync", prefix=self.prefix,
                        max_rel_dev=div["max_rel_dev"],
                        desync_steps=div["desync_steps"],
                        worst_leaf=div["worst_leaf"])
            self._last_desync_steps = div["desync_steps"]

    def to_record(self, flushed: Dict[str, Any],
                  metric: Optional[str] = None,
                  entry_point: Optional[str] = None,
                  **extra) -> Dict[str, Any]:
        """One ``kind: numerics`` JSONL payload (enrich through
        ``JsonlExporter``; validated by
        ``exporters.validate_numerics_record``)."""
        if not (metric or entry_point):
            raise ValueError("a numerics record needs a metric= or "
                             "entry_point= subject")
        rec: Dict[str, Any] = {"kind": "numerics"}
        if metric:
            rec["metric"] = metric
        if entry_point:
            rec["entry_point"] = entry_point
        for k in ("steps", "overflow_steps", "loss_scale", "half_dtype",
                  "tiny", "grad_norm", "layers", "culprit",
                  "culprit_nonfinite", "buckets", "divergence"):
            if k in flushed:
                rec[k] = flushed[k]
        rec.update(extra)
        return rec

    def record(self, state: Dict[str, Any],
               metric: Optional[str] = None,
               entry_point: Optional[str] = None,
               registry=None, **extra) -> Dict[str, Any]:
        """``flush`` + ``to_record`` in one call."""
        return self.to_record(self.flush(state, registry=registry),
                              metric=metric, entry_point=entry_point,
                              **extra)
