"""Metrics registry: counters, gauges, fixed-bucket histograms.

Two accumulation domains behind one reporting surface:

- **Host metrics** (:class:`Counter` / :class:`Gauge` / :class:`Histogram`
  owned by a :class:`MetricsRegistry`): thread-safe Python accumulation
  for eager-path instrumentation — serving step latency, data-loader
  wait times, DDP comm accounting, bench records.
- **Device metrics** (:class:`DeviceMetrics`): training-step counters
  that live *inside* the jitted step as jnp scalars threaded through the
  step carry.  ``inc`` / ``set`` / ``observe`` are pure jnp ops — zero
  host syncs per step, preserving the amp/scaler.py invariant — and
  ``flush()`` is the single explicit host fetch (one ``jax.device_get``
  of the whole state tree) that folds device totals into host metrics.

Histograms are Prometheus-shaped: fixed upper-bound bucket edges with
``le`` (<=) semantics, a running sum, and a total count; the exporter
emits the cumulative form.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Iterable, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DeviceMetrics", "get_registry", "set_registry",
           "DEFAULT_LATENCY_BUCKETS", "DEFAULT_MAX_LABEL_SETS",
           "OVERFLOW_LABEL_VALUE"]

# seconds; spans sub-ms kernel dispatches to multi-second compiles
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# cardinality cap: at most this many distinct label sets per metric.
# Label values can be user-supplied strings (tenant ids on the fleet
# serving path) — an unbounded child dict is an OOM with extra steps.
# Past the cap, new label sets fold into a shared overflow child whose
# values are all OVERFLOW_LABEL_VALUE, and the fold is counted on
# ``labels_dropped`` so the totals stay conserved AND accounted.
DEFAULT_MAX_LABEL_SETS = 64
OVERFLOW_LABEL_VALUE = "other"


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children: Dict[Tuple, "_Metric"] = {}
        self.max_label_sets = DEFAULT_MAX_LABEL_SETS
        self._labels_dropped = 0

    def _new_child(self):
        return type(self)(self.name, self.help)

    def labels(self, **labels):
        """Child metric for a label set (e.g. per-dtype comm counters);
        children are exported under the parent's name with the labels.

        Distinct label sets are capped at ``max_label_sets``: once full,
        an unseen set folds into the shared overflow child (every value
        replaced by ``OVERFLOW_LABEL_VALUE``) and ``labels_dropped``
        counts the fold — the increments still land somewhere exported,
        but a flood of user-supplied values (tenant ids) cannot grow
        the registry without bound."""
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.max_label_sets:
                    self._labels_dropped += 1
                    key = tuple((k, OVERFLOW_LABEL_VALUE)
                                for k, _ in key)
                    child = self._children.get(key)
                if child is None:
                    child = self._new_child()
                    child._label_set = key
                    self._children[key] = child
            return child

    @property
    def labels_dropped(self) -> int:
        """Label sets folded into the overflow child so far."""
        with self._lock:
            return self._labels_dropped

    def children(self):
        with self._lock:
            return dict(self._children)


class Counter(_Metric):
    """Monotonic counter."""
    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._value = 0.0

    def inc(self, value: float = 1.0):
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {value})")
        with self._lock:
            self._value += value

    def set_total(self, value: float):
        """Overwrite with an externally-accumulated monotonic total —
        the DeviceMetrics flush path (device counters already hold the
        total; adding would double-count repeated flushes)."""
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Point-in-time value."""
    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._value = 0.0

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, value: float = 1.0):
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Fixed-bucket histogram with Prometheus ``le`` (<=) edge semantics:
    an observation exactly on an edge lands in that edge's bucket."""
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help)
        edges = tuple(float(e) for e in buckets)
        if list(edges) != sorted(set(edges)):
            raise ValueError(f"histogram {name} buckets must be strictly "
                             f"increasing, got {buckets}")
        if not edges:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.edges = edges
        # per-bucket (non-cumulative) counts; last slot is the +Inf
        # overflow bucket
        self._counts = [0] * (len(edges) + 1)
        self._sum = 0.0
        self._count = 0
        # summary() memo, invalidated by every write: Engine.stats()
        # builds five summaries per read and routers/fleets read stats
        # far more often than engines observe — recomputing the
        # bucket-walk quantiles per read was the PR 4 fleet-bench drag.
        # _summary_computes counts actual recomputes (test pin).
        self._summary_cache: Optional[Dict[str, Any]] = None
        self._summary_computes = 0

    def _new_child(self):
        return Histogram(self.name, self.help, self.edges)

    def observe(self, value: float):
        idx = bisect.bisect_left(self.edges, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            self._summary_cache = None

    def _restore(self, counts: Sequence[float], total: float):
        """Overwrite from externally-accumulated totals (DeviceMetrics
        flush); ``counts`` is per-bucket non-cumulative incl. overflow."""
        if len(counts) != len(self._counts):
            raise ValueError(
                f"histogram {self.name} expects {len(self._counts)} "
                f"bucket counts, got {len(counts)}")
        with self._lock:
            self._counts = [int(c) for c in counts]
            self._count = sum(self._counts)
            self._sum = float(total)
            self._summary_cache = None

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative(self) -> Dict[str, int]:
        """{le_edge_or_'+Inf': cumulative count} — the exposition form."""
        with self._lock:
            out, acc = {}, 0
            for e, c in zip(self.edges, self._counts):
                acc += c
                out[repr(e)] = acc
            out["+Inf"] = acc + self._counts[-1]
            return out

    def _percentile_locked(self, q: float) -> Optional[float]:
        # caller holds self._lock
        if self._count == 0:
            return None
        target = q * self._count
        acc, lo = 0.0, 0.0
        for e, c in zip(self.edges, self._counts):
            if acc + c >= target and c > 0:
                frac = (target - acc) / c
                return lo + frac * (e - lo)
            acc += c
            lo = e
        return self.edges[-1]

    def percentile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate (q in [0, 1]); None when
        empty.  Values past the last edge clamp to it — fixed buckets
        cannot resolve the overflow tail."""
        with self._lock:
            return self._percentile_locked(q)

    def summary(self) -> Dict[str, Any]:
        """{count, sum, mean, p50, p99}.  Memoized between writes: a
        read-heavy consumer (``Engine.stats()`` under a fleet router)
        pays the two bucket walks once per observation, not once per
        read."""
        with self._lock:
            if self._summary_cache is None:
                count, total = self._count, self._sum
                self._summary_cache = {
                    "count": count, "sum": total,
                    "mean": (total / count) if count else None,
                    "p50": self._percentile_locked(0.5),
                    "p99": self._percentile_locked(0.99)}
                self._summary_computes += 1
            return dict(self._summary_cache)


class MetricsRegistry:
    """Named metric store; ``counter``/``gauge``/``histogram`` are
    get-or-create (a kind clash on an existing name raises)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(f"metric {name!r} already registered as "
                                 f"{m.kind}, requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS
                  ) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> Iterable[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, Any]:
        """Plain-python view: counters/gauges as numbers, histograms as
        their summary dict."""
        out = {}
        for m in self.collect():
            out[m.name] = (m.summary() if isinstance(m, Histogram)
                           else m.value)
        return out

    def clear(self):
        with self._lock:
            self._metrics.clear()


_global_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (DDP comm accounting, data
    loader timings, and DeviceMetrics flushes land here unless given an
    explicit registry)."""
    return _global_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    global _global_registry
    prev, _global_registry = _global_registry, registry
    return prev


class DeviceMetrics:
    """Device-resident metric set for jitted training steps.

    The state returned by :meth:`init` is a flat ``{name: jnp.ndarray}``
    dict — a pytree that rides the step carry like optimizer state.  All
    mutators are pure (state in, new state out) and lower to a handful
    of scalar adds, so a telemetry-enabled step emits **zero** host
    transfers (pinned by tests/test_step_graph_audit.py); the one host
    fetch is the explicit :meth:`flush`.

        dm = DeviceMetrics(counters=("steps", "overflows"),
                           gauges=("loss_scale",))
        tele = dm.init()
        # ... inside the jitted step:
        tele = dm.inc(tele, "steps")
        tele = dm.inc(tele, "overflows", info["found_inf"])
        tele = dm.set(tele, "loss_scale", info["loss_scale"])
        # ... on the host, every N steps:
        vals = dm.flush(tele)          # ONE device_get; updates registry
    """

    def __init__(self, counters: Sequence[str] = (),
                 gauges: Sequence[str] = (),
                 histograms: Optional[Dict[str, Sequence[float]]] = None,
                 prefix: str = "", registry: Optional[MetricsRegistry] = None):
        self.counters = tuple(counters)
        self.gauges = tuple(gauges)
        self.histograms = {k: tuple(float(e) for e in v)
                           for k, v in (histograms or {}).items()}
        names = (list(self.counters) + list(self.gauges)
                 + list(self.histograms))
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate metric names: {sorted(names)}")
        if not names:
            raise ValueError("DeviceMetrics needs at least one metric")
        self.prefix = prefix
        self.registry = registry

    def init(self) -> Dict[str, Any]:
        import jax.numpy as jnp
        state: Dict[str, Any] = {}
        for n in self.counters:
            state[n] = jnp.zeros((), jnp.float32)
        for n in self.gauges:
            state[n] = jnp.zeros((), jnp.float32)
        for n, edges in self.histograms.items():
            # [per-bucket counts incl. +Inf overflow..., running sum]
            state[n] = jnp.zeros((len(edges) + 2,), jnp.float32)
        return state

    def _check(self, name: str, kinds: Tuple[str, ...]):
        pools = {"counter": self.counters, "gauge": self.gauges,
                 "histogram": self.histograms}
        for k in kinds:
            if name in pools[k]:
                return
        raise KeyError(f"{name!r} is not a device {'/'.join(kinds)} "
                       f"(counters={self.counters}, gauges={self.gauges}, "
                       f"histograms={tuple(self.histograms)})")

    def inc(self, state: Dict[str, Any], name: str,
            value: Any = 1.0) -> Dict[str, Any]:
        import jax.numpy as jnp
        self._check(name, ("counter",))
        return {**state,
                name: state[name] + jnp.asarray(value, jnp.float32)}

    def set(self, state: Dict[str, Any], name: str,
            value: Any) -> Dict[str, Any]:
        import jax.numpy as jnp
        self._check(name, ("gauge",))
        return {**state, name: jnp.asarray(value, jnp.float32)}

    def observe(self, state: Dict[str, Any], name: str,
                value: Any) -> Dict[str, Any]:
        import jax.numpy as jnp
        self._check(name, ("histogram",))
        edges = jnp.asarray(self.histograms[name], jnp.float32)
        v = jnp.asarray(value, jnp.float32)
        idx = jnp.searchsorted(edges, v, side="left")  # le semantics
        buf = state[name].at[idx].add(1.0).at[-1].add(v)
        return {**state, name: buf}

    def flush(self, state: Dict[str, Any],
              registry: Optional[MetricsRegistry] = None) -> Dict[str, Any]:
        """ONE host fetch of the whole state tree; folds totals into the
        host registry (counters ``set_total``, gauges ``set``, histogram
        counts restored) and returns the plain-python values."""
        import jax
        import numpy as np
        reg = registry or self.registry or get_registry()
        host = jax.device_get(state)
        out: Dict[str, Any] = {}
        for n in self.counters:
            v = float(host[n])
            reg.counter(self.prefix + n).set_total(v)
            out[n] = v
        for n in self.gauges:
            v = float(host[n])
            reg.gauge(self.prefix + n).set(v)
            out[n] = v
        for n, edges in self.histograms.items():
            buf = np.asarray(host[n])
            counts, total = buf[:-1], float(buf[-1])
            reg.histogram(self.prefix + n,
                          buckets=edges)._restore(counts, total)
            out[n] = {"counts": [int(c) for c in counts], "sum": total}
        return out
