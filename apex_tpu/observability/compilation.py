"""Compilation-plane ledger: every jit trace/compile, measured in-process.

Apex's identity is "compile once, then run" — yet until this module the
observability plane was blind to XLA compilation itself, even though
four logged gotchas are compile-plane failures: per-replica re-jits
making cold fleet benches measure N compiles (PR 4), the
donated-executable persistent-cache reload corruption (PR 2),
concurrent compile-cache poisoning (PR 2's parallel-pytest note), and
compile seconds folded into a trended goodput rate (PR 10's bench --run
fix).  :class:`CompilationLedger` records every trace of an
instrumented jit entry — the entry label, the abstract argument
signature (leaf shapes/dtypes + static-arg values), the dispatch's wall
duration, the persistent-compilation-cache hit/miss attribution, and a
signature fingerprint — and classifies each trace's CAUSE against the
entry's previous signature via the retrace differ
(:func:`diff_signatures`), which names *which argument* changed and
how.

How traces are counted — the jit-side-effect trick: the instrumented
function body runs only while jax is TRACING (cached dispatches never
re-enter python), so a host-side ``record_trace`` call inside the
wrapped function fires exactly once per trace, with the abstract
signature computed from the tracer avals it was handed.  Steady-state
(cached) dispatches pay one thread-local push/pop and two clock reads —
no signature walk, no locks on the hot path.

Persistent-cache attribution rides ``jax.monitoring``: the
``/jax/compilation_cache/cache_hits`` / ``cache_misses`` events and the
``/jax/core/compile/backend_compile_duration`` duration fire on the
dispatching thread, so a process-wide listener attributes them to the
ledger dispatch in flight on that thread (installed lazily at the first
:func:`instrumented_jit`; absent monitoring support the cache column
reads ``uncached``).

Causes (:data:`RETRACE_CAUSES`):

- ``new_entry`` — the entry's first trace ever (the expected warmup
  compile);
- ``shape`` / ``dtype`` / ``static_arg`` — a *signature-change*
  retrace: some argument's abstract signature differs from THIS
  closure's previous trace (the diff always runs against the same
  closure's own history — two differently-shaped engines sharing an
  entry label are not each other's retraces); the differ names the
  culprit argument and its before/after signatures.  These are the
  storm class (shape-polymorphic recompilation in serving is exactly
  what ROADMAP item 1's paged-KV/chunked-prefill refactor risks) and
  the only causes that reach the flight ring (``xla_retrace`` events —
  the ``RunSupervisor``'s ``recompilation_storm`` detector feeds on
  them);
- ``new_closure`` — a *fresh* jit closure's first trace of an
  already-known entry, whatever its signature: the per-replica re-jit
  class (every ``Engine`` instance builds its own closures), which
  :meth:`~apex_tpu.fleet.Fleet.warmup` exists to pay before traffic;
- ``repeat`` — the same closure re-traced an identical signature (an
  explicit ``.lower()`` / ``make_jaxpr`` pass, or a jit cache
  eviction).

Metrics (process registry unless the ledger is given one):
``xla_traces_total{entry}``, ``xla_retraces_total{entry, cause}``,
``xla_compiles_total{entry, cache}`` (cache in hit/miss/uncached),
``xla_compile_seconds`` (wall duration of tracing dispatches).

The zero-retrace contracts are delta checks over :meth:`total_traces`:
after warmup, N mixed decode windows (serving) or a fleet failover
restarting reclaimed requests on survivors must add exactly 0 traces —
pinned in tests/test_serving.py and tests/test_fleet.py the way the
host-transfer audit pins its own invariant.

Import-light by design (stdlib only at module scope): the
``/compilez`` endpoint and tests/ci/server_smoke.py consume snapshots
without jax; :meth:`CompilationLedger.record_trace` is the jax-free
recording primitive the jit wrapper (and jax-free tests) drive.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["RETRACE_CAUSES", "SIGNATURE_CHANGE_CAUSES",
           "BENCH_COMPILE_FIELDS", "CompilationLedger",
           "abstract_signature", "diff_signatures", "format_signature",
           "signature_fingerprint", "instrumented_jit",
           "get_ledger", "set_ledger"]

# every cause a recorded trace can carry (see module docstring)
RETRACE_CAUSES = ("new_entry", "shape", "dtype", "static_arg",
                  "new_closure", "repeat")
# the storm class: a signature actually CHANGED between two traces of
# one entry — only these reach the flight ring / supervisor detector
SIGNATURE_CHANGE_CAUSES = ("shape", "dtype", "static_arg")

# the schema-v10 bench fields every fresh train/engine line carries —
# duplicated stdlib-side in exporters.COMPILE_FIELDS (pinned equal in
# tests: this module and exporters must both stay jax-free-importable)
BENCH_COMPILE_FIELDS = ("cold_compile_ms", "compiles_total",
                        "steady_state_retraces")

# compile wall durations span sub-ms toy CPU traces to minutes-scale
# hardware compiles
_COMPILE_SECONDS_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
                            1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                            300.0)

_closure_ids = itertools.count()

# per-thread stack of in-flight instrumented dispatches: the jit-time
# side effect and the jax.monitoring listeners attribute what they see
# to the top of the dispatching thread's stack
_inflight = threading.local()


def _stack() -> List["_Dispatch"]:
    st = getattr(_inflight, "stack", None)
    if st is None:
        st = _inflight.stack = []
    return st


def current_dispatch() -> Optional["_Dispatch"]:
    st = _stack()
    return st[-1] if st else None


class _Dispatch:
    """One in-flight call of an instrumented jit: collects the trace
    events recorded during it plus the cache/compile-duration events
    the monitoring listeners attribute to this thread."""

    __slots__ = ("ledger", "entry", "events", "cache_hits",
                 "cache_misses", "backend_compile_s")

    def __init__(self, ledger: "CompilationLedger", entry: str):
        self.ledger = ledger
        self.entry = entry
        self.events: List[Dict[str, Any]] = []
        self.cache_hits = 0
        self.cache_misses = 0
        self.backend_compile_s = 0.0

    @property
    def cache_label(self) -> str:
        # a partial hit (some nested executable missed) is a miss for
        # the dispatch: something was compiled fresh
        if self.cache_misses:
            return "miss"
        if self.cache_hits:
            return "hit"
        return "uncached"


# -- jax.monitoring attribution -------------------------------------------

_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_monitoring_installed = False
_monitoring_lock = threading.Lock()


def _on_monitoring_event(event: str, **kwargs):
    rec = current_dispatch()
    if rec is None:
        return
    if event == _CACHE_HIT_EVENT:
        rec.cache_hits += 1
    elif event == _CACHE_MISS_EVENT:
        rec.cache_misses += 1


def _on_monitoring_duration(event: str, duration: float, **kwargs):
    rec = current_dispatch()
    if rec is None:
        return
    if event == _BACKEND_COMPILE_EVENT:
        rec.backend_compile_s += float(duration)


def _install_monitoring():
    """Register the process-wide jax.monitoring listeners once.  The
    listeners are no-ops off the instrumented dispatch path (one
    thread-local read per event) and attribute to whatever ledger the
    in-flight dispatch belongs to, so a ``set_ledger`` swap follows."""
    global _monitoring_installed
    with _monitoring_lock:
        if _monitoring_installed:
            return
        try:
            from jax import monitoring as _mon
            _mon.register_event_listener(_on_monitoring_event)
            _mon.register_event_duration_secs_listener(
                _on_monitoring_duration)
        except Exception:       # noqa: BLE001 — API drift: the ledger
            # still counts traces; the cache column reads "uncached"
            pass
        _monitoring_installed = True


# -- abstract signatures ---------------------------------------------------

def _leaf_sig(leaf) -> List[Any]:
    """One array leaf's abstract signature: ``[dtype, shape]`` (plus a
    weak-type marker — a python scalar retraces against a committed
    array of the same dtype, and the differ must see why)."""
    aval = getattr(leaf, "aval", None)
    src = aval if aval is not None else leaf
    dtype = getattr(src, "dtype", None)
    shape = getattr(src, "shape", None)
    if dtype is None or shape is None:
        # a non-array python value closed over dynamically (jit would
        # have rejected it; keep the differ total anyway)
        return ["py", repr(type(leaf).__name__)]
    sig = [str(dtype), [int(d) for d in shape]]
    if getattr(src, "weak_type", False):
        sig.append("weak")
    return sig


def abstract_signature(args: Sequence[Any],
                       kwargs: Optional[Dict[str, Any]] = None,
                       static_argnums: Sequence[int] = (),
                       static_argnames: Sequence[str] = (),
                       arg_names: Optional[Sequence[str]] = None
                       ) -> Dict[str, Any]:
    """The per-argument abstract signature of one call: each argument
    maps to either ``{"static": repr(value)}`` or
    ``{"leaves": [[dtype, shape], ...]}`` over its pytree.  Computed at
    trace time from tracer avals (or eagerly from concrete arrays) —
    plain JSON-able python, so snapshots serve without jax."""
    import jax
    static = set(int(i) for i in static_argnums)
    names = list(arg_names or ())
    sig: Dict[str, Any] = {}
    for i, a in enumerate(args):
        name = names[i] if i < len(names) else f"arg{i}"
        if i in static:
            sig[name] = {"static": repr(a)}
        else:
            sig[name] = {"leaves": [
                _leaf_sig(leaf)
                for leaf in jax.tree_util.tree_leaves(a)]}
    snames = set(static_argnames)
    for k in sorted(kwargs or {}):
        v = (kwargs or {})[k]
        if k in snames:
            sig[k] = {"static": repr(v)}
        else:
            sig[k] = {"leaves": [
                _leaf_sig(leaf)
                for leaf in jax.tree_util.tree_leaves(v)]}
    return sig


def format_signature(arg_sig: Any) -> str:
    """Compact human form of ONE argument's signature, e.g.
    ``f32[4,8] i32[4]`` or ``static:3`` — what the ring events and
    ``/compilez`` show as before/after."""
    if not isinstance(arg_sig, dict):
        return repr(arg_sig)
    if "static" in arg_sig:
        return f"static:{arg_sig['static']}"
    parts = []
    for leaf in arg_sig.get("leaves", ()):
        dtype = str(leaf[0]) if leaf else "?"
        shape = leaf[1] if len(leaf) > 1 else None
        short = (dtype.replace("float", "f").replace("uint", "u")
                 .replace("int", "i").replace("bool", "pred")
                 .replace("bfloat", "bf"))
        dims = ",".join(str(d) for d in shape) if isinstance(
            shape, (list, tuple)) else "?"
        # the weak marker must survive into the display form: a
        # weak-vs-committed retrace (python scalar vs device array of
        # the same dtype) would otherwise show an identical
        # before/after pair — an un-actionable "nothing changed" diff
        weak = "(weak)" if "weak" in leaf[2:] else ""
        parts.append(f"{short}[{dims}]{weak}")
    return " ".join(parts) if parts else "(empty)"


def signature_fingerprint(entry: str, signature: Dict[str, Any]) -> str:
    """Stable fingerprint of (entry, abstract signature) — the identity
    two traces share iff jit would have shared their executable (same
    entry, same avals, same statics).  The cross-run join key the
    double-run cache gate compares."""
    blob = json.dumps([entry, signature], sort_keys=True,
                      default=repr).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def diff_signatures(prev: Dict[str, Any], cur: Dict[str, Any]
                    ) -> List[Dict[str, Any]]:
    """The retrace-cause differ: compare two abstract signatures of the
    same entry and name every argument whose signature changed —
    ``[{"arg", "cause", "before", "after"}, ...]`` with cause one of
    ``shape`` / ``dtype`` / ``static_arg`` (``arity`` when an argument
    appeared or vanished).  An **unchanged signature returns []** — no
    retrace cause (the trace was a fresh closure or an explicit
    re-trace, not shape polymorphism)."""
    culprits: List[Dict[str, Any]] = []
    for name in list(prev) + [n for n in cur if n not in prev]:
        a, b = prev.get(name), cur.get(name)
        if a == b:
            continue
        if a is None or b is None:
            cause = "arity"
        elif "static" in (a or {}) or "static" in (b or {}):
            cause = "static_arg"
        else:
            la = a.get("leaves", [])
            lb = b.get("leaves", [])
            if len(la) != len(lb):
                cause = "shape"
            else:
                cause = None
                for xa, xb in zip(la, lb):
                    if xa == xb:
                        continue
                    sa = xa[1] if len(xa) > 1 else None
                    sb = xb[1] if len(xb) > 1 else None
                    if sa != sb:
                        cause = "shape"
                        break
                    cause = "dtype"
                cause = cause or "dtype"
        culprits.append({"arg": name, "cause": cause,
                         "before": format_signature(a),
                         "after": format_signature(b)})
    return culprits


# -- the ledger ------------------------------------------------------------

class CompilationLedger:
    """In-process record of every instrumented jit trace/compile.

    ``registry`` / ``ring`` default to the process singletons resolved
    per use (the ``flightrec.resolve`` rule every producer follows);
    ``max_events_per_entry`` bounds the retained per-entry trace detail
    (counts stay exact forever — flight-ring discipline).
    """

    def __init__(self, registry=None, ring=None,
                 clock: Callable[[], float] = time.perf_counter,
                 max_events_per_entry: int = 64):
        self.registry = registry
        self._ring = ring
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.RLock()
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._max_events = int(max_events_per_entry)
        self._total_traces = 0
        self._total_wall_s = 0.0

    # -- default resolution (per use) ----------------------------------
    def _reg(self):
        from .metrics import get_registry
        return self.registry if self.registry is not None \
            else get_registry()

    @property
    def ring(self):
        from . import flightrec
        return flightrec.resolve(self._ring)

    # -- recording ------------------------------------------------------
    def _entry_state(self, entry: str) -> Dict[str, Any]:
        st = self._entries.get(entry)
        if st is None:
            st = self._entries[entry] = {
                "traces": 0, "retraces": 0, "compiles": 0,
                "cache": {"hit": 0, "miss": 0, "uncached": 0},
                "causes": {},
                # per-closure last signatures: the retrace diff runs
                # against the SAME closure's history (see record_trace)
                "closures": {},
                "last_signature": None, "last_closure": None,
                "last_fingerprint": None,
                "last_retrace": None,
                "compile_wall_s": 0.0, "backend_compile_s": 0.0,
                "last_trace_t_s": None,
                "events": deque(maxlen=self._max_events)}
        return st

    def record_trace(self, entry: str, signature: Dict[str, Any],
                     closure_id: Optional[int] = None,
                     dispatch: Optional[_Dispatch] = None
                     ) -> Dict[str, Any]:
        """The jax-free recording primitive: one trace of ``entry`` at
        ``signature``.  Classifies the cause against the entry's
        previous trace, updates counters, and (for signature-change
        causes) appends the ``xla_retrace`` flight event carrying the
        differ's culprit.  Returns the trace event dict."""
        t_s = round(self._clock() - self._t0, 6)
        fp = signature_fingerprint(entry, signature)
        with self._lock:
            st = self._entry_state(entry)
            closures = st["closures"]
            # a RETRACE is a closure re-tracing: the diff must run
            # against THIS closure's own previous signature.  Diffing a
            # fresh closure against another closure's signature is not
            # evidence of shape polymorphism — two differently-shaped
            # engines sharing an entry label (bench builds gpt w1/w8 +
            # llama engines back to back) would otherwise emit
            # storm-class xla_retrace events and false-positive the
            # supervisor, with a "culprit" that never varied within any
            # one closure.
            prev = closures.get(closure_id)
            if not closures and st["last_signature"] is None:
                cause, culprits = "new_entry", []
            elif prev is None:
                cause, culprits = "new_closure", []
            else:
                culprits = diff_signatures(prev, signature)
                if culprits:
                    cause = culprits[0]["cause"]
                    if cause == "arity":
                        cause = "static_arg"
                else:
                    cause = "repeat"
            closures[closure_id] = signature
            # bound the per-closure history: entries whose closures are
            # born per engine instance must not grow without limit in a
            # weeks-long process (counts stay exact forever)
            while len(closures) > 256:
                closures.pop(next(iter(closures)))
            ev: Dict[str, Any] = {
                "entry": entry, "cause": cause, "t_s": t_s,
                "fingerprint": fp,
                "signature": signature}
            if culprits:
                ev["culprits"] = culprits
                ev["culprit"] = culprits[0]["arg"]
            st["traces"] += 1
            st["causes"][cause] = st["causes"].get(cause, 0) + 1
            if cause != "new_entry":
                st["retraces"] += 1
            st["last_signature"] = signature
            st["last_closure"] = closure_id
            st["last_fingerprint"] = fp
            st["last_trace_t_s"] = t_s
            if cause in SIGNATURE_CHANGE_CAUSES:
                st["last_retrace"] = {
                    "cause": cause, "t_s": t_s,
                    "culprit": ev.get("culprit"),
                    "culprits": culprits}
            st["events"].append(ev)
            self._total_traces += 1
        reg = self._reg()
        reg.counter(
            "xla_traces_total",
            help="jit traces of instrumented entries (first compiles "
                 "and retraces alike)").labels(entry=entry).inc()
        reg.counter(
            "xla_retraces_total",
            help="traces by cause: new_entry is the warmup compile, "
                 "shape/dtype/static_arg are signature-change "
                 "retraces, new_closure the per-replica re-jit class"
        ).labels(entry=entry, cause=cause).inc()
        if cause in SIGNATURE_CHANGE_CAUSES:
            top = culprits[0] if culprits else {}
            self.ring.append("xla_retrace", entry=entry, cause=cause,
                             culprit=top.get("arg"),
                             before=top.get("before"),
                             after=top.get("after"))
        if dispatch is not None:
            dispatch.events.append(ev)
        return ev

    def _finalize_dispatch(self, rec: _Dispatch, wall_s: float):
        """Close the books on one instrumented dispatch that traced:
        the wall duration (trace + lower + compile + first execution —
        the honest 'how long did the cold call cost' number), the
        persistent-cache attribution, and the compile counters."""
        if not rec.events:
            return
        label = rec.cache_label
        with self._lock:
            st = self._entry_state(rec.entry)
            st["compiles"] += 1
            st["cache"][label] = st["cache"].get(label, 0) + 1
            st["compile_wall_s"] = round(
                st["compile_wall_s"] + wall_s, 6)
            st["backend_compile_s"] = round(
                st["backend_compile_s"] + rec.backend_compile_s, 6)
            for ev in rec.events:
                ev["wall_s"] = round(wall_s, 6)
                ev["cache"] = label
            self._total_wall_s += wall_s
        reg = self._reg()
        reg.counter(
            "xla_compiles_total",
            help="compiling dispatches by persistent-cache outcome"
        ).labels(entry=rec.entry, cache=label).inc()
        reg.histogram(
            "xla_compile_seconds",
            buckets=_COMPILE_SECONDS_BUCKETS,
            help="wall duration of dispatches that traced (trace + "
                 "lower + compile + first run)").observe(wall_s)

    # -- the jit wrapper -------------------------------------------------
    def jit(self, fun, entry: str, **kwargs):
        """:func:`instrumented_jit` bound to THIS ledger."""
        return instrumented_jit(fun, entry, ledger=self, **kwargs)

    # -- contract / snapshot surface -------------------------------------
    def total_traces(self) -> int:
        """Monotonic count of every recorded trace — the zero-retrace
        contracts are delta checks over this."""
        with self._lock:
            return self._total_traces

    def compile_wall_s(self) -> float:
        """Total wall seconds spent in tracing dispatches — what
        ``bench.py`` separates out as ``cold_compile_ms``."""
        with self._lock:
            return self._total_wall_s

    def counts(self) -> Dict[str, int]:
        """{entry: traces} snapshot."""
        with self._lock:
            return {e: st["traces"] for e, st in self._entries.items()}

    def entries(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(self._entries)

    def snapshot(self) -> Dict[str, Any]:
        """Plain-JSON ledger view — what ``/compilez`` serves.  Each
        entry carries its trace/retrace/compile counts, per-cause and
        per-cache tallies, compile seconds, the last trace's signature
        fingerprint, the last *signature-change* retrace (cause +
        the differ's culprit argument), and the bounded recent-trace
        detail."""
        with self._lock:
            entries = {}
            hits = misses = uncached = 0
            retraces = compiles = 0
            for name, st in self._entries.items():
                # events are COPIED per dict: _finalize_dispatch adds
                # wall_s/cache to the live event objects after a slow
                # compile, and a /compilez scrape serializing a shared
                # dict mid-mutation would 500 on "dictionary changed
                # size during iteration"
                entries[name] = {
                    k: ([dict(e) for e in v] if isinstance(v, deque)
                        else dict(v) if isinstance(v, dict) else v)
                    for k, v in st.items() if k != "closures"}
                hits += st["cache"].get("hit", 0)
                misses += st["cache"].get("miss", 0)
                uncached += st["cache"].get("uncached", 0)
                retraces += st["retraces"]
                compiles += st["compiles"]
            return {
                "kind": "compilation",
                "entries": entries,
                "totals": {"traces": self._total_traces,
                           "retraces": retraces,
                           "compiles": compiles,
                           "cache_hits": hits,
                           "cache_misses": misses,
                           "cache_uncached": uncached,
                           "compile_wall_s": round(self._total_wall_s,
                                                   6)},
                "uptime_s": round(self._clock() - self._t0, 3)}

    def dump(self, path: str) -> str:
        """Write the snapshot as one JSON document (atomic replace, the
        flight-ring dump discipline) — what the double-run CI gate
        reads to assert run 2's serving compiles were cache-HIT."""
        snap = self.snapshot()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, indent=2, default=repr)
            f.write("\n")
        os.replace(tmp, path)
        return path


# -- instrumentation --------------------------------------------------------

def instrumented_jit(fun, entry: str, *, ledger=None,
                     arg_names: Optional[Sequence[str]] = None,
                     static_argnums: Sequence[int] = (),
                     static_argnames: Sequence[str] = (),
                     **jit_kwargs):
    """``jax.jit`` with the compilation ledger watching: returns a
    callable that dispatches the jitted function and records every
    TRACE (entry label, abstract arg signature, wall duration,
    cache attribution) into ``ledger`` — the process ledger when None,
    resolved per dispatch so a ``set_ledger`` swap follows.

    ``arg_names`` labels the positional arguments for the retrace
    differ (falls back to the function's own signature, then
    ``arg0..``).  ``.lower`` / the underlying jit object stay reachable
    (``wrapped.lower`` / ``wrapped.jitted``) for the analysis entry
    points; an explicit ``.lower()`` or ``make_jaxpr`` pass records an
    un-timed trace (cause ``repeat`` once warm), never a compile.
    """
    import functools
    import inspect
    import jax

    _install_monitoring()
    cid = next(_closure_ids)
    sargs = tuple(int(i) for i in static_argnums)
    snames = tuple(static_argnames)
    names: Sequence[str]
    if arg_names is not None:
        names = tuple(arg_names)
    else:
        try:
            names = tuple(inspect.signature(fun).parameters)
        except (TypeError, ValueError):
            names = ()

    def _resolve(led):
        return led if led is not None else get_ledger()

    def _traced(*args, **kwargs):
        rec = current_dispatch()
        led = rec.ledger if rec is not None else _resolve(ledger)
        sig = abstract_signature(args, kwargs, static_argnums=sargs,
                                 static_argnames=snames,
                                 arg_names=names)
        led.record_trace(entry, sig, closure_id=cid, dispatch=rec)
        return fun(*args, **kwargs)

    # keep the user fn's name on the traced callable: XLA module names
    # and profiler annotations should read `_step_k`, not `_traced`
    _traced.__name__ = getattr(fun, "__name__", entry)
    _traced.__qualname__ = getattr(fun, "__qualname__",
                                   _traced.__name__)
    jitted = jax.jit(_traced, static_argnums=sargs or None,
                     static_argnames=snames or None, **jit_kwargs)

    @functools.wraps(fun)
    def wrapped(*args, **kwargs):
        led = _resolve(ledger)
        rec = _Dispatch(led, entry)
        st = _stack()
        st.append(rec)
        t0 = led._clock()
        try:
            return jitted(*args, **kwargs)
        finally:
            dt = led._clock() - t0
            # pop by identity: an exception inside a nested
            # instrumented dispatch must not strand this frame
            try:
                st.remove(rec)
            except ValueError:
                pass
            led._finalize_dispatch(rec, dt)

    wrapped.lower = jitted.lower
    wrapped.jitted = jitted
    wrapped.entry = entry
    wrapped.closure_id = cid
    if hasattr(jitted, "clear_cache"):
        wrapped.clear_cache = jitted.clear_cache
    return wrapped


# -- process singleton ------------------------------------------------------

_process_ledger = CompilationLedger()


def get_ledger() -> CompilationLedger:
    """The process-wide default ledger (every ``instrumented_jit``
    without an explicit ledger records here; ``/compilez`` serves it)."""
    return _process_ledger


def set_ledger(ledger: CompilationLedger) -> CompilationLedger:
    global _process_ledger
    prev, _process_ledger = _process_ledger, ledger
    return prev
