"""apex_tpu.data — the prefetching input pipeline.

TPU-native equivalent of the reference example's ``data_prefetcher``
(examples/imagenet/main_amp.py:264-300), which overlapped H2D copies and
normalization with compute on a side CUDA stream.  On TPU the device side
is XLA's job; the host side — batch assembly, uint8→fp32 NCHW normalize,
shuffling — is the bottleneck and runs in the C++ runtime
(apex_tpu/_native/apex_tpu_C.cpp, ``apex_loader_*``): worker threads fill
a ring of slots ahead of the training loop, delivery is in batch order,
and the Python step only wraps a ready buffer for ``device_put``.

Falls back to a pure-numpy implementation when the native library is
unavailable (the reference's Python-only build invariant).

    loader = DataLoader(images_u8_nhwc, labels, batch_size=128,
                        shuffle=True, prefetch=3, workers=4)
    for imgs, lbls in loader:           # imgs: (B, C, H, W) fp32
        ...                             # valid until the next iteration
"""

from __future__ import annotations

import ctypes
import time
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from . import _native

__all__ = ["DataLoader", "IMAGENET_MEAN", "IMAGENET_STD"]

IMAGENET_MEAN = (0.485 * 255, 0.456 * 255, 0.406 * 255)
IMAGENET_STD = (0.229 * 255, 0.224 * 255, 0.225 * 255)


class DataLoader:
    """Iterate normalized (images, labels) batches with native prefetch.

    ``images``: (N, H, W, C) uint8, ``labels``: (N,) int-like.  Epochs are
    endless via ``next_batch`` (``__iter__`` yields one epoch, drop-last).

    Delivered batches are owned copies by default.  ``zero_copy=True``
    returns views straight into the prefetch slot — fastest, but the view
    is only valid until the next ``next_batch`` call, and JAX's **CPU**
    backend may alias (not copy) aligned fp32 numpy arrays in
    ``device_put``, so an async in-flight step can read a recycled slot.
    Use zero_copy only when each batch is fully consumed (e.g.
    ``block_until_ready``) before requesting the next.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, shuffle: bool = True,
                 mean: Sequence[float] = IMAGENET_MEAN,
                 std: Sequence[float] = IMAGENET_STD,
                 prefetch: int = 3, workers: int = 4, seed: int = 0,
                 native: Optional[bool] = None, zero_copy: bool = False,
                 data_format: str = "NCHW", metrics=None):
        if data_format not in ("NCHW", "NHWC"):
            raise ValueError(f"data_format must be NCHW or NHWC, "
                             f"got {data_format!r}")
        # NHWC delivery skips the transpose entirely (a straight
        # sequential normalize walk) — pair with channels_last models so
        # the loader doesn't transpose to NCHW only for the model to
        # transpose back
        self.data_format = data_format
        self.zero_copy = zero_copy
        if np.asarray(images).dtype != np.uint8:
            raise TypeError(
                f"images must be uint8, got {np.asarray(images).dtype} — "
                "normalization happens inside the loader; pass the raw "
                "uint8 pixels")
        self.images = np.ascontiguousarray(images, np.uint8)
        self.labels = np.ascontiguousarray(labels, np.int32)
        if self.images.ndim != 4:
            raise ValueError("images must be (N, H, W, C) uint8")
        if len(self.labels) != len(self.images):
            raise ValueError("labels/images length mismatch")
        self.batch_size = int(batch_size)
        self.n, self.h, self.w, self.c = self.images.shape
        if self.n < self.batch_size:
            raise ValueError("dataset smaller than one batch")
        self.batches_per_epoch = self.n // self.batch_size
        self.shuffle = shuffle
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        if len(self.mean) != self.c or len(self.std) != self.c:
            raise ValueError("mean/std length must equal channel count")
        self.seed = seed
        self._handle = None
        self._held: Optional[ctypes.c_void_p] = None
        use_native = _native.available() if native is None else native
        if use_native and data_format == "NHWC" and _native.version() < 3:
            # stale v2 .so has the 13-arg create: it would silently fill
            # NCHW slots that we'd reshape as NHWC — scrambled pixels.
            # The numpy fallback is correct, just slower.
            use_native = False
        if use_native:
            lib = _native._try_load()
            if lib is not None:
                self._lib = lib
                create_args = [
                    self.images.ctypes.data_as(ctypes.c_void_p),
                    self.labels.ctypes.data_as(ctypes.c_void_p),
                    self.n, self.h, self.w, self.c, self.batch_size,
                    int(prefetch), int(workers), seed,
                    self.mean.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_float)),
                    self.std.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_float)),
                    1 if shuffle else 0]
                if _native.version() >= 3:
                    # the data_format arg exists only in the v3 ABI; the
                    # NHWC-on-v2 case was already routed to the numpy
                    # fallback above
                    create_args.append(1 if data_format == "NHWC" else 0)
                self._handle = lib.apex_loader_create(*create_args)
        # python fallback state
        self._py_batch = 0
        self._py_rng = np.random.RandomState(seed)
        self._py_perm = None
        self._py_epoch = -1
        # host-side load/wait telemetry: how long the training loop
        # stalls in next_batch().  Near-zero waits mean the prefetch
        # ring is ahead of compute; sustained waits mean the loader is
        # the bottleneck (the thing this pipeline exists to prevent).
        # stats() reads LOADER-LOCAL metrics; the registry (global by
        # default) additionally gets process-wide totals, which
        # aggregate across loaders sharing it.
        from .observability import get_registry
        from .observability.metrics import Counter, Histogram
        self._metrics = metrics if metrics is not None else get_registry()
        self._m_wait = Histogram(
            "data_load_wait_seconds",
            help="training-loop stall per next_batch() call")
        self._m_batches = Counter("data_batches_total")
        self._g_wait = self._metrics.histogram(
            "data_load_wait_seconds",
            help="training-loop stall per next_batch() call (all "
                 "loaders on this registry)")
        self._g_batches = self._metrics.counter(
            "data_batches_total", help="batches delivered (all loaders)")

    @property
    def native(self) -> bool:
        return self._handle is not None

    # -- native path -------------------------------------------------------
    def _next_native(self) -> Tuple[np.ndarray, np.ndarray, int]:
        if self._held is not None:
            self._lib.apex_loader_release(self._handle, self._held)
            self._held = None
        img_p = ctypes.c_void_p()
        lbl_p = ctypes.c_void_p()
        b = self._lib.apex_loader_next(self._handle, ctypes.byref(img_p),
                                       ctypes.byref(lbl_p))
        if b < 0:
            # destroy() woke us mid-wait: the slot pointers were never
            # filled — stop cleanly instead of dereferencing NULL
            raise StopIteration("data loader shut down")
        self._held = img_p
        shape = ((self.batch_size, self.h, self.w, self.c)
                 if self.data_format == "NHWC"
                 else (self.batch_size, self.c, self.h, self.w))
        imgs = np.ctypeslib.as_array(
            ctypes.cast(img_p, ctypes.POINTER(ctypes.c_float)),
            shape=shape)
        lbls = np.ctypeslib.as_array(
            ctypes.cast(lbl_p, ctypes.POINTER(ctypes.c_int32)),
            shape=(self.batch_size,))
        if not self.zero_copy:
            imgs, lbls = imgs.copy(), lbls.copy()
            # data is owned now: release the slot immediately so workers
            # refill it during this step's compute (zero_copy defers the
            # release to the next call because the views still alias it)
            self._lib.apex_loader_release(self._handle, self._held)
            self._held = None
        return imgs, lbls, b

    # -- fallback path -----------------------------------------------------
    def _next_python(self) -> Tuple[np.ndarray, np.ndarray, int]:
        b = self._py_batch
        self._py_batch += 1
        epoch, i = divmod(b, self.batches_per_epoch)
        if self.shuffle:
            if epoch != self._py_epoch:
                self._py_perm = np.random.RandomState(
                    self.seed + epoch).permutation(self.n)
                self._py_epoch = epoch
            idx = self._py_perm[i * self.batch_size:
                                (i + 1) * self.batch_size]
        else:
            idx = np.arange(i * self.batch_size, (i + 1) * self.batch_size)
        imgs = _native.preprocess_images(self.images[idx], self.mean,
                                         self.std, self.data_format)
        return imgs, self.labels[idx], b

    # -- iteration ---------------------------------------------------------
    def next_batch(self) -> Tuple[np.ndarray, np.ndarray, int]:
        """(images, labels, batch_index); endless, in batch order."""
        t0 = time.perf_counter()
        out = self._next_native() if self.native else self._next_python()
        dt = time.perf_counter() - t0
        self._m_wait.observe(dt)
        self._m_batches.inc()
        self._g_wait.observe(dt)
        self._g_batches.inc()
        return out

    def stats(self) -> dict:
        """Loader telemetry snapshot: batches delivered and the
        load/wait latency summary."""
        return {"batches": int(self._m_batches.value),
                "native": self.native,
                "load_wait": self._m_wait.summary()}

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        for _ in range(self.batches_per_epoch):
            imgs, lbls, _ = self.next_batch()
            yield imgs, lbls

    def close(self) -> None:
        if self._handle is not None:
            if self._held is not None:
                self._lib.apex_loader_release(self._handle, self._held)
                self._held = None
            self._lib.apex_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
