"""apex_tpu.data — the prefetching input pipeline.

TPU-native equivalent of the reference example's ``data_prefetcher``
(examples/imagenet/main_amp.py:264-300), which overlapped H2D copies and
normalization with compute on a side CUDA stream.  On TPU the device side
is XLA's job; the host side — batch assembly, uint8→fp32 NCHW normalize,
shuffling — is the bottleneck and runs in the C++ runtime
(apex_tpu/_native/apex_tpu_C.cpp, ``apex_loader_*``): worker threads fill
a ring of slots ahead of the training loop, delivery is in batch order,
and the Python step only wraps a ready buffer for ``device_put``.

Falls back to a pure-numpy implementation when the native library is
unavailable (the reference's Python-only build invariant).

    loader = DataLoader(images_u8_nhwc, labels, batch_size=128,
                        shuffle=True, prefetch=3, workers=4)
    for imgs, lbls in loader:           # imgs: (B, C, H, W) fp32
        ...                             # valid until the next iteration

Checkpointable, sharded iteration (PR 12).  The *portable* sample
stream — the python pipeline's per-epoch
``np.random.RandomState(seed + epoch).permutation(n)`` walk — carries
an exportable cursor: ``state_dict()`` / ``load_state_dict()`` round-
trip ``(seed, epoch, cursor, samples_consumed)`` so a preempted run
resumes with a bitwise-identical sample stream.  ``shard_id`` /
``num_shards`` shard every global batch deterministically: global step
``g`` consumes ``perm[cursor : cursor + batch_size * num_shards]`` and
shard ``s`` takes its contiguous ``batch_size`` slice, so the cursor is
WORLD-INDEPENDENT — re-deriving the shards at a different world (an
elastic 8→4 shrink) continues the same global stream and delivers every
sample exactly once.  Corrupt records are quarantined, never a crashed
step: a ``bad_record_fn`` hit is skipped (replaced in-batch by a good
sample), counted on ``data_samples_quarantined_total``, and logged to
the flight ring.  The state protocol is defined over the python
pipeline only — the native ring's shuffle order (splitmix64
Fisher–Yates) and normalize rounding are not bitwise-portable across
paths, so ``state_dict``/``load_state_dict`` raise on a native loader;
construct checkpointable loaders with ``native=False`` (``num_shards >
1`` and ``bad_record_fn`` force the python path automatically).
"""

from __future__ import annotations

import ctypes
import time
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from . import _native

__all__ = ["DataLoader", "IMAGENET_MEAN", "IMAGENET_STD"]

IMAGENET_MEAN = (0.485 * 255, 0.456 * 255, 0.406 * 255)
IMAGENET_STD = (0.229 * 255, 0.224 * 255, 0.225 * 255)


class DataLoader:
    """Iterate normalized (images, labels) batches with native prefetch.

    ``images``: (N, H, W, C) uint8, ``labels``: (N,) int-like.  Epochs are
    endless via ``next_batch`` (``__iter__`` yields one epoch, drop-last).

    Delivered batches are owned copies by default.  ``zero_copy=True``
    returns views straight into the prefetch slot — fastest, but the view
    is only valid until the next ``next_batch`` call, and JAX's **CPU**
    backend may alias (not copy) aligned fp32 numpy arrays in
    ``device_put``, so an async in-flight step can read a recycled slot.
    Use zero_copy only when each batch is fully consumed (e.g.
    ``block_until_ready``) before requesting the next.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, shuffle: bool = True,
                 mean: Sequence[float] = IMAGENET_MEAN,
                 std: Sequence[float] = IMAGENET_STD,
                 prefetch: int = 3, workers: int = 4, seed: int = 0,
                 native: Optional[bool] = None, zero_copy: bool = False,
                 data_format: str = "NCHW", metrics=None,
                 shard_id: int = 0, num_shards: int = 1,
                 bad_record_fn=None, ring=None):
        if data_format not in ("NCHW", "NHWC"):
            raise ValueError(f"data_format must be NCHW or NHWC, "
                             f"got {data_format!r}")
        # NHWC delivery skips the transpose entirely (a straight
        # sequential normalize walk) — pair with channels_last models so
        # the loader doesn't transpose to NCHW only for the model to
        # transpose back
        self.data_format = data_format
        self.zero_copy = zero_copy
        if np.asarray(images).dtype != np.uint8:
            raise TypeError(
                f"images must be uint8, got {np.asarray(images).dtype} — "
                "normalization happens inside the loader; pass the raw "
                "uint8 pixels")
        self.images = np.ascontiguousarray(images, np.uint8)
        self.labels = np.ascontiguousarray(labels, np.int32)
        if self.images.ndim != 4:
            raise ValueError("images must be (N, H, W, C) uint8")
        if len(self.labels) != len(self.images):
            raise ValueError("labels/images length mismatch")
        self.batch_size = int(batch_size)
        self.n, self.h, self.w, self.c = self.images.shape
        if self.n < self.batch_size:
            raise ValueError("dataset smaller than one batch")
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if not 0 <= shard_id < num_shards:
            raise ValueError(f"shard_id must be in [0, {num_shards}), "
                             f"got {shard_id}")
        self.shard_id = int(shard_id)
        self.num_shards = int(num_shards)
        # one GLOBAL batch is what all shards consume together per step;
        # the permutation cursor advances by it, so the cursor (and the
        # samples_consumed census) is world-independent by construction
        self.global_batch = self.batch_size * self.num_shards
        if self.n < self.global_batch:
            raise ValueError(
                f"dataset ({self.n}) smaller than one global batch "
                f"({self.global_batch} = batch_size x num_shards)")
        self.batches_per_epoch = self.n // self.global_batch
        self.bad_record_fn = bad_record_fn
        self._ring = ring
        self.shuffle = shuffle
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        if len(self.mean) != self.c or len(self.std) != self.c:
            raise ValueError("mean/std length must equal channel count")
        self.seed = seed
        self._handle = None
        self._held: Optional[ctypes.c_void_p] = None
        use_native = _native.available() if native is None else native
        if self.num_shards > 1 or bad_record_fn is not None:
            # sharded / quarantining delivery is defined over the
            # portable python permutation (the state-protocol stream);
            # the native ring knows neither shards nor record checks
            use_native = False
        if use_native and data_format == "NHWC" and _native.version() < 3:
            # stale v2 .so has the 13-arg create: it would silently fill
            # NCHW slots that we'd reshape as NHWC — scrambled pixels.
            # The numpy fallback is correct, just slower.
            use_native = False
        if use_native:
            lib = _native._try_load()
            if lib is not None:
                self._lib = lib
                create_args = [
                    self.images.ctypes.data_as(ctypes.c_void_p),
                    self.labels.ctypes.data_as(ctypes.c_void_p),
                    self.n, self.h, self.w, self.c, self.batch_size,
                    int(prefetch), int(workers), seed,
                    self.mean.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_float)),
                    self.std.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_float)),
                    1 if shuffle else 0]
                if _native.version() >= 3:
                    # the data_format arg exists only in the v3 ABI; the
                    # NHWC-on-v2 case was already routed to the numpy
                    # fallback above
                    create_args.append(1 if data_format == "NHWC" else 0)
                self._handle = lib.apex_loader_create(*create_args)
        # python fallback state: the checkpointable cursor walk.
        # (epoch, cursor) name a position in the epoch-concatenated
        # permutation stream; both are GLOBAL (shard-independent), so
        # a snapshot taken at world 8 resumes exactly at world 4.
        self._epoch = 0
        self._cursor = 0                 # samples into this epoch
        self._samples_consumed = 0       # global total across epochs
        self._batch_index = 0            # this loader's next_batch calls
        self._quarantined = 0
        self._perm = None
        self._perm_epoch = -1
        # host-side load/wait telemetry: how long the training loop
        # stalls in next_batch().  Near-zero waits mean the prefetch
        # ring is ahead of compute; sustained waits mean the loader is
        # the bottleneck (the thing this pipeline exists to prevent).
        # stats() reads LOADER-LOCAL metrics; the registry (global by
        # default) additionally gets process-wide totals, which
        # aggregate across loaders sharing it.
        from .observability import get_registry
        from .observability.metrics import Counter, Histogram
        self._metrics = metrics if metrics is not None else get_registry()
        self._m_wait = Histogram(
            "data_load_wait_seconds",
            help="training-loop stall per next_batch() call")
        self._m_batches = Counter("data_batches_total")
        self._g_wait = self._metrics.histogram(
            "data_load_wait_seconds",
            help="training-loop stall per next_batch() call (all "
                 "loaders on this registry)")
        self._g_batches = self._metrics.counter(
            "data_batches_total", help="batches delivered (all loaders)")
        self._g_quarantined = self._metrics.counter(
            "data_samples_quarantined_total",
            help="corrupt records skipped by the quarantine (never a "
                 "crashed step)")
        self._g_consumed = self._metrics.gauge(
            "data_samples_consumed",
            help="global samples consumed by the shard group this "
                 "loader belongs to (the exactly-once census)")

    @property
    def native(self) -> bool:
        return self._handle is not None

    @property
    def ring(self):
        from .observability import flightrec
        return flightrec.resolve(self._ring)

    # -- native path -------------------------------------------------------
    def _next_native(self) -> Tuple[np.ndarray, np.ndarray, int]:
        if self._held is not None:
            self._lib.apex_loader_release(self._handle, self._held)
            self._held = None
        img_p = ctypes.c_void_p()
        lbl_p = ctypes.c_void_p()
        b = self._lib.apex_loader_next(self._handle, ctypes.byref(img_p),
                                       ctypes.byref(lbl_p))
        if b < 0:
            # destroy() woke us mid-wait: the slot pointers were never
            # filled — stop cleanly instead of dereferencing NULL
            raise StopIteration("data loader shut down")
        self._held = img_p
        shape = ((self.batch_size, self.h, self.w, self.c)
                 if self.data_format == "NHWC"
                 else (self.batch_size, self.c, self.h, self.w))
        imgs = np.ctypeslib.as_array(
            ctypes.cast(img_p, ctypes.POINTER(ctypes.c_float)),
            shape=shape)
        lbls = np.ctypeslib.as_array(
            ctypes.cast(lbl_p, ctypes.POINTER(ctypes.c_int32)),
            shape=(self.batch_size,))
        if not self.zero_copy:
            imgs, lbls = imgs.copy(), lbls.copy()
            # data is owned now: release the slot immediately so workers
            # refill it during this step's compute (zero_copy defers the
            # release to the next call because the views still alias it)
            self._lib.apex_loader_release(self._handle, self._held)
            self._held = None
        return imgs, lbls, b

    # -- fallback path -----------------------------------------------------
    def _epoch_perm(self) -> np.ndarray:
        if self._perm_epoch != self._epoch:
            self._perm = (np.random.RandomState(
                self.seed + self._epoch).permutation(self.n)
                if self.shuffle else np.arange(self.n))
            self._perm_epoch = self._epoch
        return self._perm

    def _quarantine_sweep(self, idx: np.ndarray) -> np.ndarray:
        """Skip corrupt records without crashing the step: every index
        ``bad_record_fn`` flags is replaced in-batch by the first good
        sample of the same slice (batch shape must stay static for the
        jitted step), counted on ``data_samples_quarantined_total``,
        and logged to the flight ring.  The exactly-once census still
        holds for every GOOD sample; quarantined indices are accounted
        by the counter/ring, not silently re-fed to training."""
        fn = self.bad_record_fn
        if fn is None:
            return idx
        bad = [k for k in range(len(idx)) if fn(int(idx[k]))]
        if not bad:
            return idx
        idx = np.asarray(idx).copy()
        bad_set = set(bad)
        good = [k for k in range(len(idx)) if k not in bad_set]
        if good:
            sub = int(idx[good[0]])
        else:
            # a fully-poisoned batch still never crashes a STEP: fall
            # back to the first dataset record the check accepts.  A
            # fully-poisoned DATASET is the one thing that must be
            # loud — substituting a known-bad record would feed
            # training batch_size copies of exactly what the check
            # quarantined.
            sub = next((j for j in range(self.n) if not fn(j)), None)
            if sub is None:
                raise RuntimeError(
                    "every record in the dataset is flagged by "
                    "bad_record_fn — nothing left to train on")
        for k in bad:
            self._quarantined += 1
            self._g_quarantined.inc()
            self.ring.append("data_sample_quarantined",
                             index=int(idx[k]), replaced_with=sub,
                             shard=self.shard_id, epoch=self._epoch,
                             batch=self._batch_index)
            idx[k] = sub
        return idx

    def _next_python(self) -> Tuple[np.ndarray, np.ndarray, int]:
        if self._cursor + self.global_batch > self.n:
            # drop-last epoch roll (also how a cursor restored from a
            # LARGER old world lands near an epoch edge and moves on)
            self._epoch += 1
            self._cursor = 0
        perm = self._epoch_perm()
        base = self._cursor + self.shard_id * self.batch_size
        idx = perm[base:base + self.batch_size]
        self._cursor += self.global_batch
        self._samples_consumed += self.global_batch
        b = self._batch_index
        self._batch_index += 1
        idx = self._quarantine_sweep(idx)
        imgs = _native.preprocess_images(self.images[idx], self.mean,
                                         self.std, self.data_format)
        return imgs, self.labels[idx], b

    # -- iteration ---------------------------------------------------------
    def next_batch(self) -> Tuple[np.ndarray, np.ndarray, int]:
        """(images, labels, batch_index); endless, in batch order."""
        t0 = time.perf_counter()
        out = self._next_native() if self.native else self._next_python()
        dt = time.perf_counter() - t0
        self._m_wait.observe(dt)
        self._m_batches.inc()
        self._g_wait.observe(dt)
        self._g_batches.inc()
        self._g_consumed.set(float(self._census()["samples_consumed"]))
        return out

    def _census(self) -> dict:
        """The consumed-sample census (world-independent).  The python
        path reads its cursor state; the native path derives the same
        numbers from its delivered-batch counter (its stream is not
        checkpointable, but its census is still scrapeable)."""
        if self.native:
            b = int(self._m_batches.value)
            epoch, i = divmod(b, self.batches_per_epoch)
            return {"samples_consumed": b * self.global_batch,
                    "epoch": epoch, "cursor": i * self.global_batch}
        return {"samples_consumed": self._samples_consumed,
                "epoch": self._epoch, "cursor": self._cursor}

    def stats(self) -> dict:
        """Loader telemetry snapshot: batches delivered, the consumed-
        sample census (``samples_consumed``/``epoch``/``cursor``), the
        shard identity, quarantine count, and the load/wait latency
        summary — the ``/statusz`` source for the exactly-once
        accounting."""
        out = {"batches": int(self._m_batches.value),
               "native": self.native,
               "shard_id": self.shard_id,
               "num_shards": self.num_shards,
               "samples_quarantined": self._quarantined,
               "load_wait": self._m_wait.summary()}
        out.update(self._census())
        return out

    # -- checkpointable state (the preemption-safe resume protocol) --------
    def state_dict(self) -> dict:
        """Exportable cursor of the portable sample stream: everything
        a resumed loader needs to continue bitwise-identically.  All
        fields are JSON-serializable ints/bools — the checkpoint layer
        carries the blob under its content checksum
        (``utils.checkpoint.save_checkpoint(..., data_state=...)``).
        Raises on the native path: its shuffle order and normalize
        rounding are not portable; construct checkpointable loaders
        with ``native=False``."""
        if self.native:
            raise RuntimeError(
                "DataLoader.state_dict() needs the portable (python) "
                "pipeline — the native ring's shuffle order is not "
                "bitwise-portable; construct with native=False")
        return {"version": 1, "seed": int(self.seed),
                "shuffle": bool(self.shuffle), "n": int(self.n),
                "epoch": int(self._epoch), "cursor": int(self._cursor),
                "samples_consumed": int(self._samples_consumed),
                "batch_index": int(self._batch_index),
                "samples_quarantined": int(self._quarantined),
                "shard_id": int(self.shard_id),
                "num_shards": int(self.num_shards)}

    def load_state_dict(self, sd: dict) -> None:
        """Resume the portable stream at ``sd``'s cursor.  The stream
        identity (``seed``/``shuffle``/``n``) must match — resuming a
        different stream is an error, not a silent divergence.  The
        SHARDING may differ: the cursor is global, so an elastic world
        change re-derives the shards (``shard_id``/``num_shards`` of
        THIS loader win) and the global stream continues exactly
        once."""
        if self.native:
            raise RuntimeError(
                "DataLoader.load_state_dict() needs the portable "
                "(python) pipeline — construct with native=False")
        for key in ("seed", "shuffle", "n", "epoch", "cursor",
                    "samples_consumed"):
            if key not in sd:
                raise ValueError(f"data state missing {key!r}")
        if int(sd["seed"]) != self.seed:
            raise ValueError(
                f"data state was captured for seed {sd['seed']}, this "
                f"loader has seed {self.seed} — a different sample "
                f"stream cannot resume deterministically")
        if bool(sd["shuffle"]) != self.shuffle:
            raise ValueError("data state shuffle flag mismatch")
        if int(sd["n"]) != self.n:
            raise ValueError(
                f"data state names a {sd['n']}-sample dataset, this "
                f"loader holds {self.n}")
        cursor = int(sd["cursor"])
        if not 0 <= cursor <= self.n:
            raise ValueError(f"cursor {cursor} out of range [0, {self.n}]")
        self._epoch = int(sd["epoch"])
        self._cursor = cursor
        self._samples_consumed = int(sd["samples_consumed"])
        self._batch_index = int(sd.get("batch_index", 0))
        self._quarantined = int(sd.get("samples_quarantined", 0))
        self._perm_epoch = -1            # force permutation re-derive
        self._g_consumed.set(float(self._samples_consumed))

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        for _ in range(self.batches_per_epoch):
            imgs, lbls, _ = self.next_batch()
            yield imgs, lbls

    def close(self) -> None:
        if self._handle is not None:
            if self._held is not None:
                self._lib.apex_loader_release(self._handle, self._held)
                self._held = None
            self._lib.apex_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
