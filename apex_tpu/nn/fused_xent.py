"""Chunked fused linear + cross-entropy head (logits never materialized).

The reference-era pattern (and this repo's dense path) computes the LM
head as ``logits = x @ table.T`` then ``log_softmax`` in fp32 — at GPT-2
scale that materializes a (B*T, 50257) tensor twice (bf16 logits + fp32
logp) and reads it again in backward: at T=4096 that is ~1.2 GB of HBM
traffic per step for tensors that exist only to be reduced.

``linear_cross_entropy`` streams the vocabulary in chunks with an
online logsumexp (the flash-attention trick applied to the classifier
axis — same shape as multi_tensor's fused reductions): forward carries
(running max, running sumexp, label logit) per row; backward recomputes
each chunk's logits and contracts them immediately into dh and dtable.
Peak live logits: one (N, chunk) block.  Accumulations are fp32; the
matmuls run in the input dtype (bf16 under amp O2) with fp32
``preferred_element_type``, so the MXU does the work and precision
matches the dense fp32-log_softmax path to round-off (pinned by
tests/test_fused_xent.py).

Returns PER-ROW nll so callers own masking/averaging (GPT ignore_index,
sp/tp variants keep their existing semantics).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["linear_cross_entropy"]


def _dot_f32(a, b):
    """a @ b with fp32 accumulation regardless of input dtype."""
    return lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)


def _chunk_stats(h, rows, col0, labels):
    """(max, sumexp-at-max, label-logit contribution) for one chunk."""
    logits = _dot_f32(h, rows.T)                      # (N, C) fp32
    m = jnp.max(logits, axis=-1)
    s = jnp.sum(jnp.exp(logits - m[:, None]), axis=-1)
    cols = col0 + jnp.arange(rows.shape[0])
    hit = labels[:, None] == cols[None, :]
    lab = jnp.sum(jnp.where(hit, logits, 0.0), axis=-1)
    return m, s, lab


def _merge(m1, s1, m2, s2):
    m = jnp.maximum(m1, m2)
    # exp(-inf - (-inf)) cannot occur: m2 comes from finite logits
    return m, s1 * jnp.exp(m1 - m) + s2 * jnp.exp(m2 - m)


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def linear_cross_entropy(h, table, labels, chunk_size=8192):
    """Per-row ``-log softmax(h @ table.T)[label]`` without the (N, V)
    intermediate.

    h: (N, D) activations; table: (V, D) classifier/embedding rows
    (weight-tied GPT head uses the wte table directly); labels: (N,)
    int.  Rows whose label is out of range return garbage — mask
    outside (the GPT ignore_index flow already does).
    """
    nll, _ = _fwd(h, table, labels, chunk_size)
    return nll


def _fwd(h, table, labels, chunk_size):
    N, D = h.shape
    V = table.shape[0]
    C = min(chunk_size, V)
    nfull = V // C

    def body(carry, i):
        m, s, lab = carry
        rows = lax.dynamic_slice(table, (i * C, 0), (C, D))
        m2, s2, lab2 = _chunk_stats(h, rows, i * C, labels)
        m, s = _merge(m, s, m2, s2)
        return (m, s, lab + lab2), ()

    init = (jnp.full((N,), -jnp.inf, jnp.float32),
            jnp.zeros((N,), jnp.float32), jnp.zeros((N,), jnp.float32))
    (m, s, lab), _ = lax.scan(body, init, jnp.arange(nfull))
    if V % C:                                          # tail outside scan
        m2, s2, lab2 = _chunk_stats(h, table[nfull * C:], nfull * C, labels)
        m, s = _merge(m, s, m2, s2)
        lab = lab + lab2
    lse = jnp.log(s) + m
    return lse - lab, (h, table, labels, lse)


def _bwd(chunk_size, res, ct):
    h, table, labels, lse = res
    N, D = h.shape
    V = table.shape[0]
    C = min(chunk_size, V)
    nfull = V // C
    ctf = ct.astype(jnp.float32)

    def grads_for(rows, col0):
        logits = _dot_f32(h, rows.T)
        p = jnp.exp(logits - lse[:, None])
        cols = col0 + jnp.arange(rows.shape[0])
        g = (p - (labels[:, None] == cols[None, :])) * ctf[:, None]
        g = g.astype(h.dtype)
        return _dot_f32(g, rows), _dot_f32(g.T, h)     # dh (N,D), dW (C,D)

    def body(dh, i):
        rows = lax.dynamic_slice(table, (i * C, 0), (C, D))
        dh_c, dw_c = grads_for(rows, i * C)
        return dh + dh_c, dw_c

    dh, dw_full = lax.scan(body, jnp.zeros((N, D), jnp.float32),
                           jnp.arange(nfull))
    dw = dw_full.reshape(nfull * C, D)
    if V % C:
        dh_t, dw_t = grads_for(table[nfull * C:], nfull * C)
        dh = dh + dh_t
        dw = jnp.concatenate([dw, dw_t], axis=0)
    return dh.astype(h.dtype), dw.astype(table.dtype), None


linear_cross_entropy.defvjp(
    lambda h, t, l, c=8192: _fwd(h, t, l, c), _bwd)
