"""apex_tpu.nn — minimal policy-aware functional layer library."""

from .module import (Module, ModuleList, Sequential, apply, init,
                     current_context, ApplyContext)
from .layers import (Linear, Conv2d, ConvTranspose2d, BatchNorm2d, LayerNorm,
                     Embedding, Dropout, ReLU, LeakyReLU, GELU, Tanh, Sigmoid,
                     Identity, Flatten, MaxPool2d, AvgPool2d,
                     AdaptiveAvgPool2d)
from . import functional
