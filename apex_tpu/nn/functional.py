"""Policy-aware functional ops (the apex_tpu analogue of torch.nn.functional).

Every op funnels through :func:`op` → ``amp.policy.cast_op_args`` so the O1
cast policy (whitelist half, blacklist fp32, promote widest — reference
apex/amp/lists/*) applies at dispatch time.  With no policy installed the
ops are plain jnp/lax code and XLA fuses them freely.

Convolutions and pools default to NCHW layout to match the reference's
examples, and accept ``data_format="NHWC"`` for channels-last models
(channels on the TPU's 128-lane minor axis); weights stay OIHW either
way.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..amp import policy as _policy


def _check_data_format(data_format: str) -> None:
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"data_format must be NCHW or NHWC, "
                         f"got {data_format!r}")


def _bias_add(y: jax.Array, bias: Optional[jax.Array],
              data_format: str) -> jax.Array:
    if bias is None:
        return y
    b = bias.astype(y.dtype)
    return y + (b if data_format == "NHWC" else b[None, :, None, None])

__all__ = [
    "linear", "matmul", "conv2d", "conv_transpose2d", "relu", "leaky_relu",
    "gelu", "gelu_exact", "silu", "sigmoid", "tanh",
    "softmax", "log_softmax", "layer_norm", "batch_norm_stats",
    "batch_norm_apply", "dropout", "max_pool2d", "avg_pool2d",
    "adaptive_avg_pool2d", "embedding", "space_to_depth",
    "cross_entropy", "nll_loss",
    "mse_loss", "l1_loss", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "cat", "stack", "add", "mul",
]


def op(name: str):
    """Route a function through the active amp cast policy."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            args, kwargs = _policy.cast_op_args(name, args, kwargs)
            return fn(*args, **kwargs)
        wrapper.__amp_op__ = name
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# whitelist (MXU) ops
# ---------------------------------------------------------------------------

@op("linear")
def linear(x: jax.Array, weight, bias: Optional[jax.Array] = None
           ) -> jax.Array:
    # weight is (out, in) like the reference's nn.Linear.  A weight-only
    # int8 quantization.QTensor works transparently: its .T dequantizes
    # and XLA fuses the convert+scale into the dot's operand read.
    y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias
    return y


@op("matmul")
def matmul(a, b) -> jax.Array:
    return jnp.matmul(a, b)


@op("conv2d")
def conv2d(x: jax.Array, weight: jax.Array, bias: Optional[jax.Array] = None,
           stride: Union[int, Tuple[int, int]] = 1,
           padding: Union[int, Tuple[int, int], str] = 0,
           dilation: Union[int, Tuple[int, int]] = 1,
           groups: int = 1, data_format: str = "NCHW") -> jax.Array:
    """Conv with torch-shaped (O, I/groups, kH, kW) weights.

    ``data_format`` selects the activation layout: "NCHW" (torch parity,
    default) or "NHWC" (channels-last — the layout whose channel dim
    lands on the TPU's 128-lane minor axis).  The weight layout stays
    OIHW in the param tree either way — XLA consumes it directly via
    dimension_numbers, so amp casting, optimizers, and checkpoints are
    layout-agnostic."""
    _check_data_format(data_format)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    elif isinstance(padding, tuple) and isinstance(padding[0], int):
        padding = ((padding[0], padding[0]), (padding[1], padding[1]))
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=(data_format, "OIHW", data_format),
        preferred_element_type=None)
    return _bias_add(y, bias, data_format)


@op("conv_transpose2d")
def conv_transpose2d(x: jax.Array, weight: jax.Array,
                     bias: Optional[jax.Array] = None,
                     stride: Union[int, Tuple[int, int]] = 1,
                     padding: Union[int, Tuple[int, int]] = 0,
                     output_padding: Union[int, Tuple[int, int]] = 0,
                     data_format: str = "NCHW") -> jax.Array:
    """Transposed conv; weight (I, O, kH, kW) like torch; activations
    NCHW (default) or NHWC.

    Expressed as the gradient-of-conv form ``lax.conv_general_dilated``
    with lhs dilation — the formulation XLA pattern-matches onto the MXU.
    """
    _check_data_format(data_format)
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(output_padding, int):
        output_padding = (output_padding, output_padding)
    kh, kw = weight.shape[2], weight.shape[3]
    pads = tuple((k - 1 - p, k - 1 - p + op_)
                 for k, p, op_ in zip((kh, kw), padding, output_padding))
    # torch stores transposed-conv weights (in, out, kH, kW) spatially
    # unflipped; the dilated-input conv needs the flipped OIHW kernel
    w = jnp.flip(weight, axis=(2, 3)).transpose(1, 0, 2, 3)
    y = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pads,
        lhs_dilation=stride,
        dimension_numbers=(data_format, "OIHW", data_format))
    return _bias_add(y, bias, data_format)


# ---------------------------------------------------------------------------
# pointwise / activations
# ---------------------------------------------------------------------------

def relu(x: jax.Array) -> jax.Array:
    return jnp.maximum(x, 0)


def leaky_relu(x: jax.Array, negative_slope: float = 0.01) -> jax.Array:
    return jnp.where(x >= 0, x, x * negative_slope)


@op("gelu")
def gelu(x: jax.Array, approximate: bool = True) -> jax.Array:
    return jax.nn.gelu(x, approximate=approximate)

def gelu_exact(x: jax.Array) -> jax.Array:
    """erf-form gelu (HF BERT's 'gelu') — rides gelu's cast policy."""
    return gelu(x, approximate=False)




def silu(x: jax.Array) -> jax.Array:
    return x * jax.nn.sigmoid(x)


def sigmoid(x: jax.Array) -> jax.Array:
    return jax.nn.sigmoid(x)


def tanh(x: jax.Array) -> jax.Array:
    return jnp.tanh(x)


# ---------------------------------------------------------------------------
# blacklist (fp32) ops
# ---------------------------------------------------------------------------

@op("softmax")
def softmax(x: jax.Array, axis: int = -1) -> jax.Array:
    return jax.nn.softmax(x, axis=axis)


@op("log_softmax")
def log_softmax(x: jax.Array, axis: int = -1) -> jax.Array:
    return jax.nn.log_softmax(x, axis=axis)


@op("layer_norm")
def layer_norm(x: jax.Array, normalized_shape: Sequence[int],
               weight: Optional[jax.Array] = None,
               bias: Optional[jax.Array] = None, eps: float = 1e-5
               ) -> jax.Array:
    axes = tuple(range(x.ndim - len(tuple(normalized_shape)), x.ndim))
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    # shifted two-pass variance avoids E[x^2]-mean^2 cancellation
    var = jnp.mean(jnp.square(x32 - mean), axis=axes, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def batch_norm_stats(x: jax.Array, axes: Tuple[int, ...]
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-channel (count, mean, biased var) in fp32 over ``axes``.

    Single-pass E[x^2]-mean^2 with fp32 accumulation (the flax BatchNorm
    formulation): the mean and mean-of-squares reductions share one loop,
    which XLA fuses into a single HBM traversal; a shifted two-pass
    variance would serialize a second full read of ``x`` behind the mean
    (measured ~3 ms/step on ResNet-50 B=128, artifacts/PERF_NOTES_r3.md).
    It also makes local BN bitwise-consistent with the distributed path,
    which psums (count, Σx, Σx²) in the same form (parallel/
    sync_batchnorm.py; the local half of csrc/welford.cu:259-294).

    Numerics: cancellation loses ~2·log2(|mean|/std) of the 24 fp32
    mantissa bits per channel; it is catastrophic only for |mean|/std
    beyond ~2^12 — far outside any input a BN layer sees in practice.
    var is clamped at 0 so rounding can never yield a negative variance."""
    x32 = x.astype(jnp.float32)
    n = 1
    for a in axes:
        n *= x.shape[a]
    mean = jnp.mean(x32, axis=axes)
    mean_sq = jnp.mean(jnp.square(x32), axis=axes)
    var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
    return jnp.asarray(n, jnp.float32), mean, var


def batch_norm_apply(x: jax.Array, mean: jax.Array, var: jax.Array,
                     weight: Optional[jax.Array], bias: Optional[jax.Array],
                     eps: float, channel_axis: int = 1) -> jax.Array:
    from ..ops import dispatch
    # parity-test path only (pallas_forced): XLA fuses the jnp
    # scale+shift into the surrounding convs/activations for free, so a
    # standalone kernel here only adds an HBM round-trip on NCHW tiles
    # that misalign with the (8,128) layout
    if x.ndim == 4 and channel_axis == 1 and dispatch.pallas_forced():
        from ..ops.pallas_syncbn import batch_norm_apply_fused, fits_vmem
        # planes too large for the kernel's VMEM tiling fall through to
        # the jnp path below
        if fits_vmem(x.shape[2] * x.shape[3]):
            C = x.shape[1]
            w = weight if weight is not None else jnp.ones((C,), jnp.float32)
            b = bias if bias is not None else jnp.zeros((C,), jnp.float32)
            return batch_norm_apply_fused(x, mean, var, w, b, float(eps))
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = inv if weight is None else inv * weight.astype(jnp.float32)
    shift = -mean.astype(jnp.float32) * scale
    if bias is not None:
        shift = shift + bias.astype(jnp.float32)
    y = x.astype(jnp.float32) * scale.reshape(shape) + shift.reshape(shape)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# dropout / pooling / embedding
# ---------------------------------------------------------------------------

def dropout(x: jax.Array, rate: float, rng: jax.Array) -> jax.Array:
    if rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def _pool2d(x, window, stride, padding, init, reduce_fn,
            data_format="NCHW"):
    _check_data_format(data_format)
    if isinstance(window, int):
        window = (window, window)
    if stride is None:
        stride = window
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    spatial_first = 2 if data_format == "NCHW" else 1
    if isinstance(padding, (tuple, list)) and all(
            isinstance(p, int) for p in padding):
        ph, pw = padding
        pads = [(0, 0)] * 4
        pads[spatial_first] = (ph, ph)
        pads[spatial_first + 1] = (pw, pw)
        padding = tuple(pads)
    dims = [1] * 4
    strides = [1] * 4
    dims[spatial_first:spatial_first + 2] = window
    strides[spatial_first:spatial_first + 2] = stride
    return lax.reduce_window(
        x, init, reduce_fn, tuple(dims), tuple(strides), padding)


def max_pool2d(x: jax.Array, kernel_size, stride=None, padding=0,
               data_format: str = "NCHW") -> jax.Array:
    # literal init values let XLA recognize the max monoid (autodiff rule)
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
        jnp.iinfo(x.dtype).min
    return _pool2d(x, kernel_size, stride, padding, neg, lax.max,
                   data_format)


def avg_pool2d(x: jax.Array, kernel_size, stride=None, padding=0,
               data_format: str = "NCHW") -> jax.Array:
    if isinstance(kernel_size, int):
        denom = kernel_size * kernel_size
    else:
        denom = kernel_size[0] * kernel_size[1]
    s = _pool2d(x, kernel_size, stride, padding, 0.0, lax.add, data_format)
    return s / jnp.asarray(denom, x.dtype)


def adaptive_avg_pool2d(x: jax.Array, output_size: Union[int, Tuple[int, int]],
                        data_format: str = "NCHW") -> jax.Array:
    _check_data_format(data_format)
    if output_size in (1, (1, 1)):
        axes = (2, 3) if data_format == "NCHW" else (1, 2)
        return jnp.mean(x, axis=axes, keepdims=True).astype(x.dtype)
    raise NotImplementedError("adaptive_avg_pool2d supports output_size=1")


def embedding(ids: jax.Array, table) -> jax.Array:
    from ..quantization import QTensor
    if isinstance(table, QTensor):
        return table.take(ids)     # gathered rows dequantize, not the table
    return jnp.take(table, ids, axis=0)


def space_to_depth(x: jax.Array, block_size: int = 2,
                   data_format: str = "NCHW") -> jax.Array:
    """Rearrange ``block_size x block_size`` spatial tiles into channels.

    (B, C, H, W) -> (B, b*b*C, H/b, W/b) with channel index
    ``a*(b*C) + bb*C + c`` for tile offset (a, bb) — the same logical
    order in NHWC, so the two layouts are transposes of each other and
    the stem-weight converter (models.resnet.stem_weight_to_s2d) serves
    both.  Pure reshape/transpose: XLA fuses it into the consumer; on
    TPU this is the MLPerf-style stem transform that turns the
    padding-hostile 7x7/s2 cin=3 stem conv into a dense stride-1 conv
    (see models.ResNet ``stem="space_to_depth"``)."""
    _check_data_format(data_format)
    b = int(block_size)
    if b < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    if data_format == "NCHW":
        B, C, H, W = x.shape
        if H % b or W % b:
            raise ValueError(f"spatial dims {(H, W)} not divisible by "
                             f"block_size {b}")
        x = x.reshape(B, C, H // b, b, W // b, b)
        #                  0  1  2     3  4      5   -> (B, a, bb, C, H/b, W/b)
        x = x.transpose(0, 3, 5, 1, 2, 4)
        return x.reshape(B, b * b * C, H // b, W // b)
    B, H, W, C = x.shape
    if H % b or W % b:
        raise ValueError(f"spatial dims {(H, W)} not divisible by "
                         f"block_size {b}")
    x = x.reshape(B, H // b, b, W // b, b, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(B, H // b, W // b, b * b * C)


# ---------------------------------------------------------------------------
# losses (blacklist: computed in fp32)
# ---------------------------------------------------------------------------

@op("cross_entropy")
def cross_entropy(logits: jax.Array, labels: jax.Array,
                  reduction: str = "mean") -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return _reduce(nll, reduction)


@op("nll_loss")
def nll_loss(logp: jax.Array, labels: jax.Array, reduction: str = "mean"
             ) -> jax.Array:
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return _reduce(nll, reduction)


@op("mse_loss")
def mse_loss(x: jax.Array, y: jax.Array, reduction: str = "mean") -> jax.Array:
    return _reduce(jnp.square(x - y), reduction)


@op("l1_loss")
def l1_loss(x: jax.Array, y: jax.Array, reduction: str = "mean") -> jax.Array:
    return _reduce(jnp.abs(x - y), reduction)


@op("binary_cross_entropy")
def binary_cross_entropy(p: jax.Array, y: jax.Array, reduction: str = "mean"
                         ) -> jax.Array:
    # Reachable only when no policy is active or casts are disabled: under
    # an O1 policy this op name is banned (lists.BANNED_FUNCS) and raises.
    eps = 1e-12
    loss = -(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps))
    return _reduce(loss, reduction)


@op("binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(logits: jax.Array, y: jax.Array,
                                     reduction: str = "mean") -> jax.Array:
    z = logits.astype(jnp.float32)
    loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    return _reduce(loss, reduction)


def _reduce(x: jax.Array, reduction: str) -> jax.Array:
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    return x


# ---------------------------------------------------------------------------
# promote / sequence ops
# ---------------------------------------------------------------------------

@op("cat")
def cat(tensors: Sequence[jax.Array], axis: int = 0) -> jax.Array:
    return jnp.concatenate(list(tensors), axis=axis)


@op("stack")
def stack(tensors: Sequence[jax.Array], axis: int = 0) -> jax.Array:
    return jnp.stack(list(tensors), axis=axis)


@op("add")
def add(a: jax.Array, b: jax.Array) -> jax.Array:
    return a + b


@op("mul")
def mul(a: jax.Array, b: jax.Array) -> jax.Array:
    return a * b


# ---------------------------------------------------------------------------
# The full amp.lists surface (round-2 VERDICT item 8): every name the O1
# tables classify exists as a policy-aware op, so the whitelist/blacklist/
# promote guarantees hold wherever users reach for the framework's
# functional layer (the analogue of the reference patching ~200 torch entry
# points, apex/amp/amp.py:68-177).
# ---------------------------------------------------------------------------

# -- MXU whitelist: gemm family (torch_overrides.py:7-27) -------------------

@op("mm")
def mm(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.matmul(a, b)


@op("mv")
def mv(a: jax.Array, v: jax.Array) -> jax.Array:
    return jnp.matmul(a, v)


@op("bmm")
def bmm(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.matmul(a, b)


@op("addmm")
def addmm(c: jax.Array, a: jax.Array, b: jax.Array, *, beta: float = 1.0,
          alpha: float = 1.0) -> jax.Array:
    return beta * c + alpha * jnp.matmul(a, b)


@op("addmv")
def addmv(c: jax.Array, a: jax.Array, v: jax.Array, *, beta: float = 1.0,
          alpha: float = 1.0) -> jax.Array:
    return beta * c + alpha * jnp.matmul(a, v)


@op("addr")
def addr(c: jax.Array, u: jax.Array, v: jax.Array, *, beta: float = 1.0,
         alpha: float = 1.0) -> jax.Array:
    return beta * c + alpha * jnp.outer(u, v)


@op("addbmm")
def addbmm(c: jax.Array, a: jax.Array, b: jax.Array, *, beta: float = 1.0,
           alpha: float = 1.0) -> jax.Array:
    return beta * c + alpha * jnp.sum(jnp.matmul(a, b), axis=0)


@op("baddbmm")
def baddbmm(c: jax.Array, a: jax.Array, b: jax.Array, *, beta: float = 1.0,
            alpha: float = 1.0) -> jax.Array:
    return beta * c + alpha * jnp.matmul(a, b)


@op("prelu")
def prelu(x: jax.Array, weight: jax.Array) -> jax.Array:
    w = weight.reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 else weight
    return jnp.where(x >= 0, x, w.astype(x.dtype) * x)


# -- MXU whitelist: conv family ---------------------------------------------

def _convnd(x, weight, stride, padding, dilation, groups, nd):
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(dilation, int):
        dilation = (dilation,) * nd
    if isinstance(padding, int):
        padding = ((padding, padding),) * nd
    elif (isinstance(padding, tuple)
          and all(isinstance(p, int) for p in padding)):
        padding = tuple((p, p) for p in padding)
    spatial = "DHW"[-nd:] if nd <= 3 else None
    lhs = "NC" + spatial
    rhs = "OI" + spatial
    return lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=(lhs, rhs, lhs))


@op("conv1d")
def conv1d(x: jax.Array, weight: jax.Array,
           bias: Optional[jax.Array] = None, stride=1, padding=0,
           dilation=1, groups: int = 1) -> jax.Array:
    """NCW conv; weight (O, I/groups, kW) like torch."""
    y = _convnd(x, weight, stride, padding, dilation, groups, 1)
    if bias is not None:
        y = y + bias.astype(y.dtype)[None, :, None]
    return y


@op("conv3d")
def conv3d(x: jax.Array, weight: jax.Array,
           bias: Optional[jax.Array] = None, stride=1, padding=0,
           dilation=1, groups: int = 1) -> jax.Array:
    """NCDHW conv; weight (O, I/groups, kD, kH, kW) like torch."""
    y = _convnd(x, weight, stride, padding, dilation, groups, 3)
    if bias is not None:
        y = y + bias.astype(y.dtype)[None, :, None, None, None]
    return y


def _conv_transposend(x, weight, stride, padding, nd):
    if isinstance(stride, int):
        stride = (stride,) * nd
    if isinstance(padding, int):
        padding = (padding,) * nd
    spatial = "DHW"[-nd:]
    lhs = "NC" + spatial
    rhs = "OI" + spatial
    k = weight.shape[2:]
    pads = tuple((ki - 1 - p, ki - 1 - p) for ki, p in zip(k, padding))
    w = jnp.swapaxes(weight, 0, 1)
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    return lax.conv_general_dilated(
        x, w, window_strides=(1,) * nd, padding=pads, lhs_dilation=stride,
        dimension_numbers=(lhs, rhs, lhs))


@op("conv_transpose1d")
def conv_transpose1d(x: jax.Array, weight: jax.Array,
                     bias: Optional[jax.Array] = None, stride=1,
                     padding=0) -> jax.Array:
    """NCW transposed conv; weight (I, O, kW) like torch."""
    y = _conv_transposend(x, weight, stride, padding, 1)
    if bias is not None:
        y = y + bias.astype(y.dtype)[None, :, None]
    return y


@op("conv_transpose3d")
def conv_transpose3d(x: jax.Array, weight: jax.Array,
                     bias: Optional[jax.Array] = None, stride=1,
                     padding=0) -> jax.Array:
    """NCDHW transposed conv; weight (I, O, kD, kH, kW) like torch."""
    y = _conv_transposend(x, weight, stride, padding, 3)
    if bias is not None:
        y = y + bias.astype(y.dtype)[None, :, None, None, None]
    return y


@op("conv_tbc")
def conv_tbc(x: jax.Array, weight: jax.Array, bias: Optional[jax.Array],
             pad: int = 0) -> jax.Array:
    """Time×Batch×Channels conv (torch.conv_tbc): x (T, B, Cin), weight
    (kW, Cin, Cout)."""
    ncw = jnp.transpose(x, (1, 2, 0))                 # (B, Cin, T)
    w = jnp.transpose(weight, (2, 1, 0))              # (Cout, Cin, kW)
    y = lax.conv_general_dilated(
        ncw, w, window_strides=(1,), padding=((pad, pad),),
        dimension_numbers=("NCW", "OIW", "NCW"))
    y = jnp.transpose(y, (2, 0, 1))                   # (T', B, Cout)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


# -- fp32 blacklist: pointwise transcendentals ------------------------------

def _fp32_unary(name, fn):
    @op(name)
    @functools.wraps(fn)
    def wrapper(x, *args, **kwargs):
        return fn(x, *args, **kwargs)
    wrapper.__name__ = name
    wrapper.__qualname__ = name
    return wrapper


exp = _fp32_unary("exp", jnp.exp)
expm1 = _fp32_unary("expm1", jnp.expm1)
log = _fp32_unary("log", jnp.log)
log10 = _fp32_unary("log10", jnp.log10)
log2 = _fp32_unary("log2", jnp.log2)
log1p = _fp32_unary("log1p", jnp.log1p)
reciprocal = _fp32_unary("reciprocal", jnp.reciprocal)
rsqrt = _fp32_unary("rsqrt", lax.rsqrt)
acos = _fp32_unary("acos", jnp.arccos)
asin = _fp32_unary("asin", jnp.arcsin)
cosh = _fp32_unary("cosh", jnp.cosh)
sinh = _fp32_unary("sinh", jnp.sinh)
tan = _fp32_unary("tan", jnp.tan)
erf = _fp32_unary("erf", jax.scipy.special.erf)
erfinv = _fp32_unary("erfinv", jax.scipy.special.erfinv)
cumsum = _fp32_unary("cumsum", jnp.cumsum)
cumprod = _fp32_unary("cumprod", jnp.cumprod)


@op("pow")
def pow(x: jax.Array, exponent) -> jax.Array:  # noqa: A001 (torch name)
    return jnp.power(x, exponent)


@op("softplus")
def softplus(x: jax.Array, beta: float = 1.0,
             threshold: float = 20.0) -> jax.Array:
    scaled = beta * x
    # clamp the exp argument: where() evaluates both branches, and an
    # overflowed exp would turn the dead branch's zero cotangent into
    # 0*inf = NaN in the backward pass
    safe = jnp.log1p(jnp.exp(jnp.minimum(scaled, threshold))) / beta
    return jnp.where(scaled > threshold, x, safe)


# -- fp32 blacklist: reductions ---------------------------------------------

sum = _fp32_unary("sum", jnp.sum)        # noqa: A001 (torch name)
mean = _fp32_unary("mean", jnp.mean)
prod = _fp32_unary("prod", jnp.prod)
std = _fp32_unary("std", functools.partial(jnp.std, ddof=1))
var = _fp32_unary("var", functools.partial(jnp.var, ddof=1))
logsumexp = _fp32_unary("logsumexp", jax.scipy.special.logsumexp)


@op("norm")
def norm(x: jax.Array, p: float = 2.0, axis=None,
         keepdims: bool = False) -> jax.Array:
    if p == 2.0:
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdims) ** (1.0 / p)


@op("dist")
def dist(a: jax.Array, b: jax.Array, p: float = 2.0) -> jax.Array:
    d = a - b
    if p == 2.0:
        return jnp.sqrt(jnp.sum(jnp.square(d)))
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


@op("renorm")
def renorm(x: jax.Array, p: float, axis: int, maxnorm: float) -> jax.Array:
    """Per-slice (along ``axis``) p-norm clamp to maxnorm (torch.renorm)."""
    moved = jnp.moveaxis(x, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    if p == 2.0:
        norms = jnp.sqrt(jnp.sum(jnp.square(flat), axis=1))
    else:
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
    factor = jnp.where(norms > maxnorm, maxnorm / (norms + 1e-7), 1.0)
    out = flat * factor[:, None]
    return jnp.moveaxis(out.reshape(moved.shape), 0, axis)


@op("softmin")
def softmin(x: jax.Array, axis: int = -1) -> jax.Array:
    return jax.nn.softmax(-x, axis=axis)


@op("normalize")
def normalize(x: jax.Array, p: float = 2.0, axis: int = 1,
              eps: float = 1e-12) -> jax.Array:
    if p == 2.0:
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    else:
        n = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(n, eps)


@op("cosine_similarity")
def cosine_similarity(a: jax.Array, b: jax.Array, axis: int = 1,
                      eps: float = 1e-8) -> jax.Array:
    num = jnp.sum(a * b, axis=axis)
    na = jnp.sqrt(jnp.sum(jnp.square(a), axis=axis))
    nb = jnp.sqrt(jnp.sum(jnp.square(b), axis=axis))
    return num / jnp.maximum(na * nb, eps)


@op("pdist")
def pdist(x: jax.Array, p: float = 2.0) -> jax.Array:
    """Condensed pairwise distances of the rows of x (N, D)."""
    n = x.shape[0]
    diff = x[:, None, :] - x[None, :, :]
    if p == 2.0:
        d = jnp.sqrt(jnp.sum(jnp.square(diff), axis=-1) + 1e-30)
    else:
        d = jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)
    iu, ju = jnp.triu_indices(n, k=1)
    return d[iu, ju]


# -- fp32 blacklist: norms ---------------------------------------------------

@op("group_norm")
def group_norm(x: jax.Array, num_groups: int,
               weight: Optional[jax.Array] = None,
               bias: Optional[jax.Array] = None,
               eps: float = 1e-5) -> jax.Array:
    N, C = x.shape[:2]
    g = x.reshape(N, num_groups, C // num_groups, *x.shape[2:])
    axes = tuple(range(2, g.ndim))
    mean_ = jnp.mean(g, axis=axes, keepdims=True)
    var_ = jnp.mean(jnp.square(g - mean_), axis=axes, keepdims=True)
    out = ((g - mean_) * lax.rsqrt(var_ + eps)).reshape(x.shape)
    shape = (1, C) + (1,) * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@op("instance_norm")
def instance_norm(x: jax.Array, weight: Optional[jax.Array] = None,
                  bias: Optional[jax.Array] = None,
                  eps: float = 1e-5) -> jax.Array:
    axes = tuple(range(2, x.ndim))
    mean_ = jnp.mean(x, axis=axes, keepdims=True)
    var_ = jnp.mean(jnp.square(x - mean_), axis=axes, keepdims=True)
    out = (x - mean_) * lax.rsqrt(var_ + eps)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@op("batch_norm")
def batch_norm(x: jax.Array, running_mean: Optional[jax.Array],
               running_var: Optional[jax.Array],
               weight: Optional[jax.Array] = None,
               bias: Optional[jax.Array] = None, training: bool = False,
               momentum: float = 0.1, eps: float = 1e-5) -> jax.Array:
    """Stateless F.batch_norm parity (stats updates live in the BatchNorm
    modules; here running stats are inputs)."""
    if training or running_mean is None:
        axes = (0,) + tuple(range(2, x.ndim))
        _, mean_, var_ = batch_norm_stats(x, axes)
    else:
        mean_, var_ = running_mean, running_var
    return batch_norm_apply(x, mean_, var_, weight, bias, eps)


# -- fp32 blacklist: losses --------------------------------------------------

@op("smooth_l1_loss")
def smooth_l1_loss(x: jax.Array, target: jax.Array, beta: float = 1.0,
                   reduction: str = "mean") -> jax.Array:
    d = jnp.abs(x - target)
    loss = jnp.where(d < beta, 0.5 * d * d / beta, d - 0.5 * beta)
    return _reduce(loss, reduction)


@op("kl_div")
def kl_div(log_pred: jax.Array, target: jax.Array,
           reduction: str = "mean", log_target: bool = False) -> jax.Array:
    if log_target:
        loss = jnp.exp(target) * (target - log_pred)
    else:
        loss = jnp.where(target > 0, target * (jnp.log(
            jnp.maximum(target, 1e-38)) - log_pred), 0.0)
    if reduction == "batchmean":
        return jnp.sum(loss) / log_pred.shape[0]
    return _reduce(loss, reduction)


@op("soft_margin_loss")
def soft_margin_loss(x: jax.Array, target: jax.Array,
                     reduction: str = "mean") -> jax.Array:
    return _reduce(jnp.log1p(jnp.exp(-target * x)), reduction)


@op("poisson_nll_loss")
def poisson_nll_loss(log_input: jax.Array, target: jax.Array,
                     log_input_form: bool = True, full: bool = False,
                     eps: float = 1e-8,
                     reduction: str = "mean") -> jax.Array:
    if log_input_form:
        loss = jnp.exp(log_input) - target * log_input
    else:
        loss = log_input - target * jnp.log(log_input + eps)
    if full:
        stirling = (target * jnp.log(jnp.maximum(target, 1.0))
                    - target + 0.5 * jnp.log(2 * jnp.pi *
                                             jnp.maximum(target, 1.0)))
        loss = loss + jnp.where(target > 1, stirling, 0.0)
    return _reduce(loss, reduction)


@op("cosine_embedding_loss")
def cosine_embedding_loss(a: jax.Array, b: jax.Array, target: jax.Array,
                          margin: float = 0.0,
                          reduction: str = "mean") -> jax.Array:
    cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
        jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-8)
    loss = jnp.where(target == 1, 1.0 - cos,
                     jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


@op("hinge_embedding_loss")
def hinge_embedding_loss(x: jax.Array, target: jax.Array,
                         margin: float = 1.0,
                         reduction: str = "mean") -> jax.Array:
    loss = jnp.where(target == 1, x, jnp.maximum(0.0, margin - x))
    return _reduce(loss, reduction)


@op("margin_ranking_loss")
def margin_ranking_loss(x1: jax.Array, x2: jax.Array, target: jax.Array,
                        margin: float = 0.0,
                        reduction: str = "mean") -> jax.Array:
    return _reduce(jnp.maximum(0.0, -target * (x1 - x2) + margin), reduction)


@op("triplet_margin_loss")
def triplet_margin_loss(anchor: jax.Array, positive: jax.Array,
                        negative: jax.Array, margin: float = 1.0,
                        p: float = 2.0,
                        reduction: str = "mean") -> jax.Array:
    dp = jnp.sum(jnp.abs(anchor - positive) ** p, axis=-1) ** (1.0 / p)
    dn = jnp.sum(jnp.abs(anchor - negative) ** p, axis=-1) ** (1.0 / p)
    return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)


@op("multi_margin_loss")
def multi_margin_loss(x: jax.Array, target: jax.Array, p: float = 1.0,
                      margin: float = 1.0,
                      reduction: str = "mean") -> jax.Array:
    N, C = x.shape
    xy = x[jnp.arange(N), target][:, None]
    loss = jnp.maximum(0.0, margin - xy + x) ** p
    loss = loss.at[jnp.arange(N), target].set(0.0)
    return _reduce(jnp.sum(loss, axis=1) / C, reduction)


@op("multilabel_margin_loss")
def multilabel_margin_loss(x: jax.Array, target: jax.Array,
                           reduction: str = "mean") -> jax.Array:
    """torch semantics: per sample, target holds class indices padded with
    -1 after the first -1; loss sums max(0, 1 - (x[y] - x[k])) over target
    classes y and non-target classes k, / C."""
    N, C = x.shape
    first_neg = jnp.argmax(target < 0, axis=1)
    has_neg = jnp.any(target < 0, axis=1)
    count = jnp.where(has_neg, first_neg, C)          # valid targets
    pos_mask = jnp.arange(C)[None, :] < count[:, None]  # (N, C) positions
    tgt = jnp.where(pos_mask, target, 0)
    is_target = jnp.zeros((N, C), bool).at[
        jnp.repeat(jnp.arange(N), C),
        tgt.reshape(-1)].max(pos_mask.reshape(-1))
    xy = jnp.take_along_axis(x, tgt, axis=1)          # (N, C) target scores
    # pairwise: for each valid target slot j and non-target class k
    diff = 1.0 - (xy[:, :, None] - x[:, None, :])     # (N, C, C)
    valid = (pos_mask[:, :, None]
             & ~is_target[:, None, :])
    loss = jnp.sum(jnp.where(valid, jnp.maximum(0.0, diff), 0.0),
                   axis=(1, 2)) / C
    return _reduce(loss, reduction)


# -- promote ops -------------------------------------------------------------

@op("sub")
def sub(a: jax.Array, b: jax.Array) -> jax.Array:
    return a - b


@op("div")
def div(a: jax.Array, b: jax.Array) -> jax.Array:
    return a / b


@op("addcdiv")
def addcdiv(x: jax.Array, a: jax.Array, b: jax.Array,
            value: float = 1.0) -> jax.Array:
    return x + value * (a / b)


@op("addcmul")
def addcmul(x: jax.Array, a: jax.Array, b: jax.Array,
            value: float = 1.0) -> jax.Array:
    return x + value * (a * b)


@op("atan2")
def atan2(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.arctan2(a, b)


@op("cross")
def cross(a: jax.Array, b: jax.Array, axis: int = -1) -> jax.Array:
    return jnp.cross(a, b, axis=axis)


@op("dot")
def dot(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.dot(a, b)


@op("bilinear")
def bilinear(x1: jax.Array, x2: jax.Array, weight: jax.Array,
             bias: Optional[jax.Array] = None) -> jax.Array:
    """torch.nn.functional.bilinear: weight (out, in1, in2)."""
    y = jnp.einsum("...i,oij,...j->...o", x1, weight, x2)
    if bias is not None:
        y = y + bias
    return y


@op("eq")
def eq(a, b):
    return a == b


@op("ne")
def ne(a, b):
    return a != b


@op("lt")
def lt(a, b):
    return a < b


@op("gt")
def gt(a, b):
    return a > b


@op("le")
def le(a, b):
    return a <= b


@op("ge")
def ge(a, b):
    return a >= b


@op("equal")
def equal(a, b):
    return jnp.array_equal(a, b)


@op("min")
def min(a, b=None, **kwargs):          # noqa: A001 (torch name)
    if b is None:
        return jnp.min(a, **kwargs)
    return jnp.minimum(a, b)


@op("max")
def max(a, b=None, **kwargs):          # noqa: A001 (torch name)
    if b is None:
        return jnp.max(a, **kwargs)
    return jnp.maximum(a, b)


@op("fmod")
def fmod(a, b):
    return jnp.fmod(a, b)


@op("remainder")
def remainder(a, b):
    return jnp.remainder(a, b)


@op("concatenate")
def concatenate(tensors: Sequence[jax.Array], axis: int = 0) -> jax.Array:
    return jnp.concatenate(list(tensors), axis=axis)


__all__ += [
    "mm", "mv", "bmm", "addmm", "addmv", "addr", "addbmm", "baddbmm",
    "prelu", "conv1d", "conv3d", "conv_transpose1d", "conv_transpose3d",
    "conv_tbc",
    "exp", "expm1", "log", "log10", "log2", "log1p", "reciprocal", "rsqrt",
    "acos", "asin", "cosh", "sinh", "tan", "erf", "erfinv", "cumsum",
    "cumprod", "pow", "softplus",
    "sum", "mean", "prod", "std", "var", "logsumexp", "norm", "dist",
    "renorm", "softmin", "normalize", "cosine_similarity", "pdist",
    "group_norm", "instance_norm", "batch_norm",
    "smooth_l1_loss", "kl_div", "soft_margin_loss", "poisson_nll_loss",
    "cosine_embedding_loss", "hinge_embedding_loss", "margin_ranking_loss",
    "triplet_margin_loss", "multi_margin_loss", "multilabel_margin_loss",
    "sub", "div", "addcdiv", "addcmul", "atan2", "cross", "dot", "bilinear",
    "eq", "ne", "lt", "gt", "le", "ge", "equal", "min", "max", "fmod",
    "remainder", "concatenate",
]
