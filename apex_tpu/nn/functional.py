"""Policy-aware functional ops (the apex_tpu analogue of torch.nn.functional).

Every op funnels through :func:`op` → ``amp.policy.cast_op_args`` so the O1
cast policy (whitelist half, blacklist fp32, promote widest — reference
apex/amp/lists/*) applies at dispatch time.  With no policy installed the
ops are plain jnp/lax code and XLA fuses them freely.

Convolutions use NCHW layout to match the reference's examples; XLA
re-layouts internally for the MXU so this costs nothing at runtime.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..amp import policy as _policy

__all__ = [
    "linear", "matmul", "conv2d", "conv_transpose2d", "relu", "leaky_relu",
    "gelu", "silu", "sigmoid", "tanh",
    "softmax", "log_softmax", "layer_norm", "batch_norm_stats",
    "batch_norm_apply", "dropout", "max_pool2d", "avg_pool2d",
    "adaptive_avg_pool2d", "embedding", "cross_entropy", "nll_loss",
    "mse_loss", "l1_loss", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "cat", "stack", "add", "mul",
]


def op(name: str):
    """Route a function through the active amp cast policy."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            args, kwargs = _policy.cast_op_args(name, args, kwargs)
            return fn(*args, **kwargs)
        wrapper.__amp_op__ = name
        return wrapper
    return deco


# ---------------------------------------------------------------------------
# whitelist (MXU) ops
# ---------------------------------------------------------------------------

@op("linear")
def linear(x: jax.Array, weight: jax.Array, bias: Optional[jax.Array] = None
           ) -> jax.Array:
    # weight is (out, in) like the reference's nn.Linear
    y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias
    return y


@op("matmul")
def matmul(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.matmul(a, b)


@op("conv2d")
def conv2d(x: jax.Array, weight: jax.Array, bias: Optional[jax.Array] = None,
           stride: Union[int, Tuple[int, int]] = 1,
           padding: Union[int, Tuple[int, int], str] = 0,
           dilation: Union[int, Tuple[int, int]] = 1,
           groups: int = 1) -> jax.Array:
    """NCHW conv; weight (O, I/groups, kH, kW) like torch."""
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(dilation, int):
        dilation = (dilation, dilation)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    elif isinstance(padding, tuple) and isinstance(padding[0], int):
        padding = ((padding[0], padding[0]), (padding[1], padding[1]))
    y = lax.conv_general_dilated(
        x, weight, window_strides=stride, padding=padding,
        rhs_dilation=dilation, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=None)
    if bias is not None:
        y = y + bias.astype(y.dtype)[None, :, None, None]
    return y


@op("conv_transpose2d")
def conv_transpose2d(x: jax.Array, weight: jax.Array,
                     bias: Optional[jax.Array] = None,
                     stride: Union[int, Tuple[int, int]] = 1,
                     padding: Union[int, Tuple[int, int]] = 0,
                     output_padding: Union[int, Tuple[int, int]] = 0
                     ) -> jax.Array:
    """NCHW transposed conv; weight (I, O, kH, kW) like torch.

    Expressed as the gradient-of-conv form ``lax.conv_general_dilated``
    with lhs dilation — the formulation XLA pattern-matches onto the MXU.
    """
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(output_padding, int):
        output_padding = (output_padding, output_padding)
    kh, kw = weight.shape[2], weight.shape[3]
    pads = tuple((k - 1 - p, k - 1 - p + op_)
                 for k, p, op_ in zip((kh, kw), padding, output_padding))
    # torch stores transposed-conv weights (in, out, kH, kW) spatially
    # unflipped; the dilated-input conv needs the flipped OIHW kernel
    w = jnp.flip(weight, axis=(2, 3)).transpose(1, 0, 2, 3)
    y = lax.conv_general_dilated(
        x, w, window_strides=(1, 1), padding=pads,
        lhs_dilation=stride,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if bias is not None:
        y = y + bias.astype(y.dtype)[None, :, None, None]
    return y


# ---------------------------------------------------------------------------
# pointwise / activations
# ---------------------------------------------------------------------------

def relu(x: jax.Array) -> jax.Array:
    return jnp.maximum(x, 0)


def leaky_relu(x: jax.Array, negative_slope: float = 0.01) -> jax.Array:
    return jnp.where(x >= 0, x, x * negative_slope)


@op("gelu")
def gelu(x: jax.Array, approximate: bool = True) -> jax.Array:
    return jax.nn.gelu(x, approximate=approximate)


def silu(x: jax.Array) -> jax.Array:
    return x * jax.nn.sigmoid(x)


def sigmoid(x: jax.Array) -> jax.Array:
    return jax.nn.sigmoid(x)


def tanh(x: jax.Array) -> jax.Array:
    return jnp.tanh(x)


# ---------------------------------------------------------------------------
# blacklist (fp32) ops
# ---------------------------------------------------------------------------

@op("softmax")
def softmax(x: jax.Array, axis: int = -1) -> jax.Array:
    return jax.nn.softmax(x, axis=axis)


@op("log_softmax")
def log_softmax(x: jax.Array, axis: int = -1) -> jax.Array:
    return jax.nn.log_softmax(x, axis=axis)


@op("layer_norm")
def layer_norm(x: jax.Array, normalized_shape: Sequence[int],
               weight: Optional[jax.Array] = None,
               bias: Optional[jax.Array] = None, eps: float = 1e-5
               ) -> jax.Array:
    axes = tuple(range(x.ndim - len(tuple(normalized_shape)), x.ndim))
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    # shifted two-pass variance avoids E[x^2]-mean^2 cancellation
    var = jnp.mean(jnp.square(x32 - mean), axis=axes, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def batch_norm_stats(x: jax.Array, axes: Tuple[int, ...]
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per-channel (count, mean, biased var) in fp32 over ``axes``.
    Shifted two-pass variance (no E[x^2]-mean^2 cancellation) — the local
    half of the reference's Welford stats (csrc/welford.cu:259-294)."""
    x32 = x.astype(jnp.float32)
    n = 1
    for a in axes:
        n *= x.shape[a]
    mean = jnp.mean(x32, axis=axes)
    shape = [1] * x.ndim
    for a in range(x.ndim):
        if a not in axes:
            shape[a] = x.shape[a]
    var = jnp.mean(jnp.square(x32 - mean.reshape(shape)), axis=axes)
    return jnp.asarray(n, jnp.float32), mean, var


def batch_norm_apply(x: jax.Array, mean: jax.Array, var: jax.Array,
                     weight: Optional[jax.Array], bias: Optional[jax.Array],
                     eps: float, channel_axis: int = 1) -> jax.Array:
    from ..ops import dispatch
    # parity-test path only (pallas_forced): XLA fuses the jnp
    # scale+shift into the surrounding convs/activations for free, so a
    # standalone kernel here only adds an HBM round-trip on NCHW tiles
    # that misalign with the (8,128) layout
    if x.ndim == 4 and channel_axis == 1 and dispatch.pallas_forced():
        from ..ops.pallas_syncbn import batch_norm_apply_fused, fits_vmem
        # planes too large for the kernel's VMEM tiling fall through to
        # the jnp path below
        if fits_vmem(x.shape[2] * x.shape[3]):
            C = x.shape[1]
            w = weight if weight is not None else jnp.ones((C,), jnp.float32)
            b = bias if bias is not None else jnp.zeros((C,), jnp.float32)
            return batch_norm_apply_fused(x, mean, var, w, b, float(eps))
    shape = [1] * x.ndim
    shape[channel_axis] = x.shape[channel_axis]
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = inv if weight is None else inv * weight.astype(jnp.float32)
    shift = -mean.astype(jnp.float32) * scale
    if bias is not None:
        shift = shift + bias.astype(jnp.float32)
    y = x.astype(jnp.float32) * scale.reshape(shape) + shift.reshape(shape)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# dropout / pooling / embedding
# ---------------------------------------------------------------------------

def dropout(x: jax.Array, rate: float, rng: jax.Array) -> jax.Array:
    if rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def _pool2d(x, window, stride, padding, init, reduce_fn):
    if isinstance(window, int):
        window = (window, window)
    if stride is None:
        stride = window
    if isinstance(stride, int):
        stride = (stride, stride)
    if isinstance(padding, int):
        padding = (padding, padding)
    if isinstance(padding, (tuple, list)) and all(
            isinstance(p, int) for p in padding):
        ph, pw = padding
        padding = ((0, 0), (0, 0), (ph, ph), (pw, pw))
    return lax.reduce_window(
        x, init, reduce_fn, (1, 1) + tuple(window), (1, 1) + tuple(stride),
        padding)


def max_pool2d(x: jax.Array, kernel_size, stride=None, padding=0) -> jax.Array:
    # literal init values let XLA recognize the max monoid (autodiff rule)
    neg = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
        jnp.iinfo(x.dtype).min
    return _pool2d(x, kernel_size, stride, padding, neg, lax.max)


def avg_pool2d(x: jax.Array, kernel_size, stride=None, padding=0) -> jax.Array:
    if isinstance(kernel_size, int):
        denom = kernel_size * kernel_size
    else:
        denom = kernel_size[0] * kernel_size[1]
    s = _pool2d(x, kernel_size, stride, padding, 0.0, lax.add)
    return s / jnp.asarray(denom, x.dtype)


def adaptive_avg_pool2d(x: jax.Array, output_size: Union[int, Tuple[int, int]]
                        ) -> jax.Array:
    if output_size in (1, (1, 1)):
        return jnp.mean(x, axis=(2, 3), keepdims=True).astype(x.dtype)
    raise NotImplementedError("adaptive_avg_pool2d supports output_size=1")


def embedding(ids: jax.Array, table: jax.Array) -> jax.Array:
    return jnp.take(table, ids, axis=0)


# ---------------------------------------------------------------------------
# losses (blacklist: computed in fp32)
# ---------------------------------------------------------------------------

@op("cross_entropy")
def cross_entropy(logits: jax.Array, labels: jax.Array,
                  reduction: str = "mean") -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return _reduce(nll, reduction)


@op("nll_loss")
def nll_loss(logp: jax.Array, labels: jax.Array, reduction: str = "mean"
             ) -> jax.Array:
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return _reduce(nll, reduction)


@op("mse_loss")
def mse_loss(x: jax.Array, y: jax.Array, reduction: str = "mean") -> jax.Array:
    return _reduce(jnp.square(x - y), reduction)


@op("l1_loss")
def l1_loss(x: jax.Array, y: jax.Array, reduction: str = "mean") -> jax.Array:
    return _reduce(jnp.abs(x - y), reduction)


@op("binary_cross_entropy")
def binary_cross_entropy(p: jax.Array, y: jax.Array, reduction: str = "mean"
                         ) -> jax.Array:
    # Reachable only when no policy is active or casts are disabled: under
    # an O1 policy this op name is banned (lists.BANNED_FUNCS) and raises.
    eps = 1e-12
    loss = -(y * jnp.log(p + eps) + (1 - y) * jnp.log(1 - p + eps))
    return _reduce(loss, reduction)


@op("binary_cross_entropy_with_logits")
def binary_cross_entropy_with_logits(logits: jax.Array, y: jax.Array,
                                     reduction: str = "mean") -> jax.Array:
    z = logits.astype(jnp.float32)
    loss = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    return _reduce(loss, reduction)


def _reduce(x: jax.Array, reduction: str) -> jax.Array:
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    return x


# ---------------------------------------------------------------------------
# promote / sequence ops
# ---------------------------------------------------------------------------

@op("cat")
def cat(tensors: Sequence[jax.Array], axis: int = 0) -> jax.Array:
    return jnp.concatenate(list(tensors), axis=axis)


@op("stack")
def stack(tensors: Sequence[jax.Array], axis: int = 0) -> jax.Array:
    return jnp.stack(list(tensors), axis=axis)


@op("add")
def add(a: jax.Array, b: jax.Array) -> jax.Array:
    return a + b


@op("mul")
def mul(a: jax.Array, b: jax.Array) -> jax.Array:
    return a * b
