"""Functional module system for apex_tpu.

The reference toolkit (NVIDIA Apex) layers itself on torch.nn's stateful
modules and monkey-patches their internals (apex/amp/_initialize.py:197-208,
apex/amp/amp.py:68-177).  On TPU/JAX the idiomatic shape is functional: a
module is a *description* (hyperparameters + submodule tree) and parameters
live in an external pytree.  ``Module`` here provides:

- automatic submodule registration via attribute assignment (like torch.nn),
- ``init(key)`` producing a nested params dict mirroring the attribute tree,
- mutable-state handling (BatchNorm running stats) through a flat,
  path-keyed state dict threaded by :func:`apply` — so user ``forward``
  code only passes params, exactly like torch code only passes tensors,
- train/eval and RNG plumbing through an apply-context, so dropout and
  batchnorm behave like ``model.train()`` / ``model.eval()`` without the
  user threading flags through every call.

Everything is jit-safe: the context only ever holds tracers that came in
through :func:`apply`'s arguments, and state updates are returned
functionally.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "Module",
    "ModuleList",
    "Sequential",
    "apply",
    "init",
    "current_context",
    "ApplyContext",
]


class _ContextStack(threading.local):
    def __init__(self):
        self.stack: List["ApplyContext"] = []


_CTX = _ContextStack()


class ApplyContext:
    """Per-apply bookkeeping: mutable state in/out, train flag, RNGs."""

    def __init__(self, state: Optional[Dict[str, Any]], train: bool,
                 rng: Optional[jax.Array], mutable: bool):
        self.state_in: Dict[str, Any] = dict(state or {})
        self.state_out: Dict[str, Any] = {}
        self.train = bool(train)
        self.mutable = bool(mutable)
        self._rng = rng
        self._rng_count = 0

    # -- state ------------------------------------------------------------
    def get_state(self, path: str) -> Any:
        if path in self.state_out:
            return self.state_out[path]
        return self.state_in.get(path)

    def set_state(self, path: str, value: Any) -> None:
        if self.mutable:
            self.state_out[path] = value

    # -- rng --------------------------------------------------------------
    def make_rng(self) -> jax.Array:
        if self._rng is None:
            raise ValueError(
                "This apply() needs an rng= argument (a module used dropout "
                "or another stochastic op in train mode).")
        self._rng_count += 1
        return jax.random.fold_in(self._rng, self._rng_count)

    def merged_state(self) -> Dict[str, Any]:
        out = dict(self.state_in)
        out.update(self.state_out)
        return out


def current_context() -> Optional[ApplyContext]:
    return _CTX.stack[-1] if _CTX.stack else None


class Module:
    """Base class: a hyperparameter container with a named submodule tree."""

    def __init__(self):
        object.__setattr__(self, "_children", {})
        object.__setattr__(self, "_parent", None)
        object.__setattr__(self, "_name", None)

    # -- tree plumbing ----------------------------------------------------
    def __setattr__(self, name: str, value: Any) -> None:
        if isinstance(value, Module):
            self._children[name] = value
            object.__setattr__(value, "_parent", self)
            object.__setattr__(value, "_name", name)
        elif name in self._children and not isinstance(value, Module):
            del self._children[name]
        object.__setattr__(self, name, value)

    def _replace_child(self, name: str, new: "Module") -> None:
        """Swap a registered child (used by convert_syncbn_model-style passes)."""
        setattr(self, name, new)

    @property
    def path(self) -> str:
        parts: List[str] = []
        node: Optional[Module] = self
        while node is not None and node._name is not None:
            parts.append(node._name)
            node = node._parent
        return ".".join(reversed(parts))

    def named_children(self) -> Iterator[Tuple[str, "Module"]]:
        return iter(list(self._children.items()))

    def modules(self) -> Iterator["Module"]:
        yield self
        for _, c in self.named_children():
            yield from c.modules()

    def named_modules(self, prefix: str = "") -> Iterator[Tuple[str, "Module"]]:
        yield prefix, self
        for name, c in self.named_children():
            sub = f"{prefix}.{name}" if prefix else name
            yield from c.named_modules(sub)

    # -- parameter / state creation --------------------------------------
    def create_params(self, key: jax.Array) -> Dict[str, Any]:
        """Leaf hook: return this module's own parameter dict (no children)."""
        return {}

    def create_state(self) -> Optional[Dict[str, Any]]:
        """Leaf hook: return this module's own mutable state dict, if any."""
        return None

    def init(self, key: jax.Array) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Build (params, state) for this module and all descendants.

        ``params`` is nested mirroring attribute names; ``state`` is flat,
        keyed by dotted module path (jit-friendly and immune to the param
        tree being sliced by optimizers).
        """
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        own_key, child_key = jax.random.split(key) if self._children else (key, None)
        own = self.create_params(own_key)
        if own:
            params.update(own)
        own_state = self.create_state()
        if own_state is not None:
            state[self.path] = own_state
        if self._children:
            keys = jax.random.split(child_key, len(self._children))
            for (name, child), k in zip(self._children.items(), keys):
                p, s = child.init(k)
                if p:
                    params[name] = p
                state.update(s)
        return params, state

    # -- forward ----------------------------------------------------------
    def forward(self, params: Dict[str, Any], *args, **kwargs):
        raise NotImplementedError(type(self).__name__)

    def __call__(self, params: Dict[str, Any], *args, **kwargs):
        return self.forward(params, *args, **kwargs)

    def apply(self, params: Dict[str, Any], *args,
              state: Optional[Dict[str, Any]] = None, train: bool = False,
              rng: Optional[jax.Array] = None, mutable: bool = True,
              **kwargs):
        """Functional apply returning ``(out, new_state)`` — see the
        module-level :func:`apply`."""
        return apply(self, params, *args, state=state, train=train,
                     rng=rng, mutable=mutable, **kwargs)

    # -- conveniences -----------------------------------------------------
    def sub(self, params: Dict[str, Any], name: str) -> Dict[str, Any]:
        return params.get(name, {})

    def __repr__(self) -> str:
        lines = [type(self).__name__ + "("]
        for name, c in self.named_children():
            body = repr(c).splitlines()
            lines.append(f"  ({name}): " + body[0])
            lines.extend("  " + b for b in body[1:])
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else type(self).__name__ + "()"


class ModuleList(Module):
    """An indexable list of submodules, registered as children '0','1',..."""

    def __init__(self, mods: Optional[List[Module]] = None):
        super().__init__()
        self._len = 0
        for m in (mods or []):
            self.append(m)

    def append(self, mod: Module) -> "ModuleList":
        setattr(self, str(self._len), mod)
        self._len += 1
        return self

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, idx: int) -> Module:
        if isinstance(idx, slice):
            return [getattr(self, str(i)) for i in range(*idx.indices(self._len))]
        if idx < 0:
            idx += self._len
        return getattr(self, str(idx))

    def __iter__(self) -> Iterator[Module]:
        return (self[i] for i in range(self._len))

    def __setitem__(self, idx: int, mod: Module) -> None:
        if idx < 0:
            idx += self._len
        setattr(self, str(idx), mod)


class Sequential(ModuleList):
    """Chains children; each child is called as child(params[name], x)."""

    def forward(self, params, x):
        for i, mod in enumerate(self):
            x = mod(params.get(str(i), {}), x)
        return x


def init(module: Module, key: jax.Array) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    return module.init(key)


def apply(module: Module, params: Dict[str, Any], *args,
          state: Optional[Dict[str, Any]] = None, train: bool = False,
          rng: Optional[jax.Array] = None, mutable: bool = True, **kwargs):
    """Run ``module`` functionally.

    Returns ``(out, new_state)``. ``new_state`` equals ``state`` with any
    updates applied (BatchNorm running stats in train mode, etc.).  With
    ``mutable=False`` state writes are dropped and ``new_state is state``-
    equivalent, which keeps eval paths trivially pure.
    """
    ctx = ApplyContext(state, train, rng, mutable)
    _CTX.stack.append(ctx)
    try:
        out = module(params, *args, **kwargs)
    finally:
        _CTX.stack.pop()
    return out, ctx.merged_state()
