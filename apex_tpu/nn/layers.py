"""Layer library: the minimal set the reference's examples/tests exercise
(Linear/Conv/BatchNorm/LayerNorm/activations/pooling/dropout/embedding),
policy-aware via apex_tpu.nn.functional.

BatchNorm keeps fp32 parameters and statistics under amp by default — the
`keep_batchnorm_fp32` invariant the reference enforces via convert_network
(apex/fp16_utils/fp16util.py:60-70) and the O2 preset
(apex/amp/frontend.py:133-143); layers whose class sets ``fp32_params=True``
are skipped by amp's param-casting pass.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from . import functional as F
from .module import Module, current_context

__all__ = [
    "Linear", "Conv2d", "ConvTranspose2d", "BatchNorm2d", "LayerNorm",
    "Embedding", "Dropout", "ReLU", "LeakyReLU", "GELU", "Tanh", "Sigmoid",
    "Identity", "Flatten", "MaxPool2d", "AvgPool2d", "AdaptiveAvgPool2d",
]


def _kaiming_uniform(key, shape, fan_in, dtype=jnp.float32):
    bound = math.sqrt(1.0 / fan_in)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


class Linear(Module):
    def __init__(self, in_features: int, out_features: int, bias: bool = True):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias

    def create_params(self, key):
        wk, bk = jax.random.split(key)
        p = {"weight": _kaiming_uniform(
            wk, (self.out_features, self.in_features), self.in_features)}
        if self.use_bias:
            p["bias"] = _kaiming_uniform(
                bk, (self.out_features,), self.in_features)
        return p

    def forward(self, params, x):
        return F.linear(x, params["weight"], params.get("bias"))


class Conv2d(Module):
    def __init__(self, in_channels: int, out_channels: int,
                 kernel_size: Union[int, Tuple[int, int]],
                 stride: Union[int, Tuple[int, int]] = 1,
                 padding: Union[int, Tuple[int, int]] = 0,
                 dilation: int = 1, groups: int = 1, bias: bool = True,
                 data_format: str = "NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.use_bias = bias
        self.data_format = data_format

    def create_params(self, key):
        wk, bk = jax.random.split(key)
        fan_in = (self.in_channels // self.groups) * \
            self.kernel_size[0] * self.kernel_size[1]
        p = {"weight": _kaiming_uniform(
            wk, (self.out_channels, self.in_channels // self.groups,
                 *self.kernel_size), fan_in)}
        if self.use_bias:
            p["bias"] = _kaiming_uniform(bk, (self.out_channels,), fan_in)
        return p

    def forward(self, params, x):
        return F.conv2d(x, params["weight"], params.get("bias"),
                        stride=self.stride, padding=self.padding,
                        dilation=self.dilation, groups=self.groups,
                        data_format=self.data_format)


class ConvTranspose2d(Module):
    """Transposed convolution (DCGAN generator upsampling path); NCHW
    default, NHWC via data_format."""

    def __init__(self, in_channels: int, out_channels: int,
                 kernel_size: Union[int, Tuple[int, int]],
                 stride: Union[int, Tuple[int, int]] = 1,
                 padding: Union[int, Tuple[int, int]] = 0,
                 output_padding: Union[int, Tuple[int, int]] = 0,
                 bias: bool = True, data_format: str = "NCHW"):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size, kernel_size)
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.use_bias = bias
        self.data_format = data_format

    def create_params(self, key):
        wk, bk = jax.random.split(key)
        # torch derives transposed-conv fan_in from weight.size(1)
        # (= out_channels), not in_channels
        fan_in = self.out_channels * self.kernel_size[0] * self.kernel_size[1]
        p = {"weight": _kaiming_uniform(
            wk, (self.in_channels, self.out_channels, *self.kernel_size),
            fan_in)}
        if self.use_bias:
            p["bias"] = _kaiming_uniform(bk, (self.out_channels,), fan_in)
        return p

    def forward(self, params, x):
        return F.conv_transpose2d(x, params["weight"], params.get("bias"),
                                  stride=self.stride, padding=self.padding,
                                  output_padding=self.output_padding,
                                  data_format=self.data_format)


class LeakyReLU(Module):
    def __init__(self, negative_slope: float = 0.01):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, params, x):
        return F.leaky_relu(x, self.negative_slope)


class BatchNorm2d(Module):
    """NCHW batch norm with running statistics in apply-context state.

    fp32_params=True marks its affine params (and stats) to stay fp32 under
    amp O2 (reference: keep_batchnorm_fp32, apex/amp/frontend.py:133-143).
    """

    fp32_params = True

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 track_running_stats: bool = True, channel_axis: int = 1):
        super().__init__()
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.track_running_stats = track_running_stats
        # 1 for NCHW (torch parity, default); -1/3 for channels-last
        self.channel_axis = channel_axis

    def create_params(self, key):
        if not self.affine:
            return {}
        return {"weight": jnp.ones((self.num_features,), jnp.float32),
                "bias": jnp.zeros((self.num_features,), jnp.float32)}

    def create_state(self):
        if not self.track_running_stats:
            return None
        return {"running_mean": jnp.zeros((self.num_features,), jnp.float32),
                "running_var": jnp.ones((self.num_features,), jnp.float32),
                "num_batches_tracked": jnp.zeros((), jnp.int64
                                                 if jax.config.jax_enable_x64
                                                 else jnp.int32)}

    # hook for SyncBatchNorm: merge (count, mean, var) across devices
    def _sync_stats(self, count, mean, var):
        return count, mean, var

    def forward(self, params, x):
        ctx = current_context()
        train = ctx.train if ctx is not None else False
        st = ctx.get_state(self.path) if (ctx is not None and
                                          self.track_running_stats) else None
        if train or st is None:
            ca = self.channel_axis % x.ndim
            axes = tuple(a for a in range(x.ndim) if a != ca)
            count, mean, var = F.batch_norm_stats(x, axes)
            count, mean, var = self._sync_stats(count, mean, var)
            if st is not None and ctx.mutable:
                m = self.momentum
                # unbiased variance for the running estimate, matching the
                # reference (apex/parallel/sync_batchnorm.py:123-131)
                unbiased = var * (count / jnp.maximum(count - 1.0, 1.0))
                ctx.set_state(self.path, {
                    "running_mean": (1 - m) * st["running_mean"] + m * mean,
                    "running_var": (1 - m) * st["running_var"] + m * unbiased,
                    "num_batches_tracked": st["num_batches_tracked"] + 1,
                })
        else:
            mean, var = st["running_mean"], st["running_var"]
        w = params.get("weight") if self.affine else None
        b = params.get("bias") if self.affine else None
        return F.batch_norm_apply(x, mean, var, w, b, self.eps,
                                  channel_axis=self.channel_axis)


class LayerNorm(Module):
    fp32_params = True

    def __init__(self, normalized_shape: Union[int, Sequence[int]],
                 eps: float = 1e-5, elementwise_affine: bool = True):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine

    def create_params(self, key):
        if not self.elementwise_affine:
            return {}
        return {"weight": jnp.ones(self.normalized_shape, jnp.float32),
                "bias": jnp.zeros(self.normalized_shape, jnp.float32)}

    def forward(self, params, x):
        return F.layer_norm(x, self.normalized_shape, params.get("weight"),
                            params.get("bias"), self.eps)


class Embedding(Module):
    """``init_std`` defaults to torch's nn.Embedding N(0, 1); the
    transformer families pass their conventional 0.02
    (initializer_range) so scratch training starts at ~uniform loss
    instead of the ~9x-hot logits a unit-variance tied head produces."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 init_std: float = 1.0):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.init_std = init_std

    def create_params(self, key):
        return {"weight": self.init_std * jax.random.normal(
            key, (self.num_embeddings, self.embedding_dim), jnp.float32)}

    def forward(self, params, ids):
        return F.embedding(ids, params["weight"])


class Dropout(Module):
    def __init__(self, rate: float = 0.5):
        super().__init__()
        self.rate = rate

    def forward(self, params, x):
        ctx = current_context()
        if ctx is None or not ctx.train or self.rate == 0.0:
            return x
        return F.dropout(x, self.rate, ctx.make_rng())


class ReLU(Module):
    def forward(self, params, x):
        return F.relu(x)


class GELU(Module):
    def forward(self, params, x):
        return F.gelu(x)


class Tanh(Module):
    def forward(self, params, x):
        return F.tanh(x)


class Sigmoid(Module):
    def forward(self, params, x):
        return F.sigmoid(x)


class Identity(Module):
    def forward(self, params, x):
        return x


class Flatten(Module):
    def forward(self, params, x):
        return x.reshape(x.shape[0], -1)


class MaxPool2d(Module):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format: str = "NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, params, x):
        return F.max_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class AvgPool2d(Module):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format: str = "NCHW"):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.data_format = data_format

    def forward(self, params, x):
        return F.avg_pool2d(x, self.kernel_size, self.stride, self.padding,
                            self.data_format)


class AdaptiveAvgPool2d(Module):
    def __init__(self, output_size=1, data_format: str = "NCHW"):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, params, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)
